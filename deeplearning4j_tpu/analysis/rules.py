"""graftlint rule engine: trace-safety + distributed-correctness rules.

| rule              | set it runs on        | hazard                               |
|-------------------|-----------------------|--------------------------------------|
| host-sync         | hot (dispatch path)   | device→host pull stalls the pipeline |
| retrace-hazard    | everything            | per-call compiles / cache misses     |
| jit-purity        | traced                | value baked at trace time / silent   |
| numpy-on-tracer   | traced                | TracerArrayConversionError / consts  |
| lock-discipline   | threaded modules      | unguarded shared mutable state       |
| monotonic-clock   | everything            | wall clock in duration arithmetic    |
| cost-analysis-off-hot-path | traced + hot | HLO cost walk / trace export per batch |
| tuner-off-hot-path | traced + hot         | tuner search/trial (compiles, subprocesses, timers) per batch |
| step-wiring       | nn/ + parallel/       | donated-carry jit built outside nn/step_program.py |
| use-after-donate  | dataflow (donations)  | read of a buffer donated into a step |
| collective-consistency | shard_map bodies | rank-divergent / axis-mismatched collectives |
| durable-store-protocol | dataflow (paths) | raw (non-atomic) writes on durable store paths |

The last three run on the interprocedural field-sensitive dataflow layer
(``Index.dataflow``) and live in :mod:`analysis.rules_distributed`; this
module re-exports them through :data:`ALL_RULES` / :func:`run` so the CLI
and baseline treat every rule uniformly.

Each checker yields ``engine.Finding`` objects; inline
``# graftlint: disable=<rule>`` suppressions are honored by
``Index.make_finding`` (same line or the line above).
"""

from __future__ import annotations

import ast
from typing import Callable, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.engine import (
    MUTATOR_METHODS,
    Finding,
    FunctionInfo,
    Index,
    dotted_name,
    is_jit_call,
    own_nodes,
)
from deeplearning4j_tpu.analysis.rules_distributed import (
    DISTRIBUTED_RULES,
    run_distributed,
)

__all__ = ["ALL_RULES", "run"]

ALL_RULES = (
    "host-sync",
    "retrace-hazard",
    "jit-purity",
    "numpy-on-tracer",
    "lock-discipline",
    "monotonic-clock",
    "cost-analysis-off-hot-path",
    "tuner-off-hot-path",
    "step-wiring",
) + DISTRIBUTED_RULES

# numpy calls that only touch metadata — safe on tracers and device arrays
NP_METADATA_OK = {
    "shape", "ndim", "size", "dtype", "result_type", "issubdtype",
    "broadcast_shapes", "iterable", "isscalar",
}

IMPURE_CALLS = {
    "time.time": "time.time() is baked in at trace time (every later call "
                 "reuses the traced value); use a traced input instead",
    "time.time_ns": "time.time_ns() is baked in at trace time",
    "time.monotonic": "time.monotonic() is baked in at trace time",
    "datetime.datetime.now": "datetime.now() is baked in at trace time",
    "datetime.datetime.utcnow": "datetime.utcnow() is baked in at trace time",
}


def run(index: Index, rules: Optional[Sequence[str]] = None) -> List[Finding]:
    active = set(rules) if rules else set(ALL_RULES)
    out: List[Finding] = []
    if "host-sync" in active:
        out += _rule_host_sync(index)
    if "retrace-hazard" in active:
        out += _rule_retrace_hazard(index)
    if "jit-purity" in active:
        out += _rule_jit_purity(index)
    if "numpy-on-tracer" in active:
        out += _rule_numpy_on_tracer(index)
    if "lock-discipline" in active:
        out += _rule_lock_discipline(index)
    if "monotonic-clock" in active:
        out += _rule_monotonic_clock(index)
    if "cost-analysis-off-hot-path" in active:
        out += _rule_cost_analysis_off_hot_path(index)
    if "tuner-off-hot-path" in active:
        out += _rule_tuner_off_hot_path(index)
    if "step-wiring" in active:
        out += _rule_step_wiring(index)
    if active & set(DISTRIBUTED_RULES):
        out += run_distributed(index, sorted(active & set(DISTRIBUTED_RULES)))
    # drop duplicates (one line can trip a rule through several sub-checks)
    seen: Set[tuple] = set()
    uniq = []
    for f in out:
        key = (f.rule, f.path, f.line, f.func)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


# ---------------------------------------------------------------------------
# taint: which local names hold device values / tracer values
# ---------------------------------------------------------------------------


def _device_taint(
    fi: FunctionInfo, index: Index, seed_params: bool,
) -> Tuple[Set[str], Callable[[ast.AST], bool]]:
    """Names in ``fi`` that plausibly hold device/tracer values — parameters
    (for traced functions), plus anything assigned (or loop-iterated) from a
    jax/jnp call, a jitted-callable dispatch, or a call into the hot /
    device-source sets — and a predicate testing whether an expression
    involves such a value. Two linear passes over the body reach a fixpoint
    for ordinary straight-line reassignment chains."""
    tainted: Set[str] = set(fi.params) if seed_params else set()

    def call_is_source(call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in index.jit_names:
            return True
        if (isinstance(f, ast.Name) and f.id in index.jit_names
                and f.id in fi.module.global_names):
            return True
        d = dotted_name(f, fi.module)
        if d and d.startswith("jax."):
            return True
        return any(c in index.hot or c in index.device_sources
                   for c in index.resolve_call(fi, f))

    def expr_tainted(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in tainted):
                return True
            if isinstance(n, ast.Call) and call_is_source(n):
                return True
        return False

    def taint_target(t: ast.AST):
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                tainted.add(n.id)

    nodes = own_nodes(fi.node)
    for _ in range(2):
        before = len(tainted)
        for node in nodes:
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for t in node.targets:
                    taint_target(t)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and expr_tainted(node.value):
                taint_target(node.target)
            elif isinstance(node, ast.AugAssign) and expr_tainted(node.value):
                taint_target(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)) and expr_tainted(node.iter):
                taint_target(node.target)
        if len(tainted) == before:
            break
    return tainted, expr_tainted


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------


def _rule_host_sync(index: Index) -> List[Finding]:
    out = []
    for q in sorted(index.hot):
        fi = index.functions[q]
        _, tainted = _device_taint(fi, index, seed_params=False)
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func, fi.module)
            f = None
            if d == "jax.device_get":
                f = index.make_finding(
                    "host-sync", fi, node.lineno,
                    "jax.device_get in jit dispatch path: blocking "
                    "device→host transfer")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args
                  and tainted(node.func.value)):
                f = index.make_finding(
                    "host-sync", fi, node.lineno,
                    ".item() on a device value in the jit dispatch path: "
                    "synchronous host round-trip per call")
            elif d in ("numpy.asarray", "numpy.array", "numpy.copy") \
                    and node.args and any(tainted(a) for a in node.args):
                f = index.make_finding(
                    "host-sync", fi, node.lineno,
                    f"{d.replace('numpy', 'np')} on a device value in the "
                    "jit dispatch path: pulls the array back to host")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "int", "bool")
                  and node.args and tainted(node.args[0])):
                f = index.make_finding(
                    "host-sync", fi, node.lineno,
                    f"{node.func.id}() on a device value in the jit dispatch "
                    "path: blocks until the executable finishes")
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------


def _static_spec_is_literal(v: ast.AST) -> bool:
    if isinstance(v, ast.Constant):
        return isinstance(v.value, (int, str))
    if isinstance(v, (ast.Tuple, ast.List)):
        return all(isinstance(e, ast.Constant) and isinstance(e.value, (int, str))
                   for e in v.elts)
    return False


def _rule_retrace_hazard(index: Index) -> List[Finding]:
    out = []

    def check_jit_call(fi: FunctionInfo, call: ast.Call, loop_depth: int):
        if loop_depth > 0:
            f = index.make_finding(
                "retrace-hazard", fi, call.lineno,
                "jax.jit constructed inside a loop: a fresh jit wrapper per "
                "iteration compiles (and caches) separately every time")
            if f:
                out.append(f)
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames") \
                    and not _static_spec_is_literal(kw.value):
                f = index.make_finding(
                    "retrace-hazard", fi, call.lineno,
                    f"{kw.arg} is not a literal int/str (tuple): non-hashable "
                    "or array-valued static specs retrace per call or fail "
                    "to cache")
                if f:
                    out.append(f)

    def scan(fi: FunctionInfo, node: ast.AST, loop_depth: int):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, ast.Call):
                if is_jit_call(child, fi.module):
                    check_jit_call(fi, child, loop_depth)
                if isinstance(child.func, ast.Call) \
                        and is_jit_call(child.func, fi.module):
                    f = index.make_finding(
                        "retrace-hazard", fi, child.lineno,
                        "jax.jit(f)(...) constructs and discards the jitted "
                        "wrapper per call: the compile cache is keyed on the "
                        "wrapper, so this can retrace every invocation")
                    if f:
                        out.append(f)
            d = loop_depth + (1 if isinstance(child, (ast.For, ast.AsyncFor,
                                                      ast.While)) else 0)
            scan(fi, child, d)

    for q in sorted(index.functions):
        fi = index.functions[q]
        scan(fi, fi.node, 0)

    # traced closures over mutable module state: the captured value is baked
    # into the executable at trace time — later mutations are silently stale
    for q in sorted(index.traced):
        fi = index.functions.get(q)
        if fi is None or isinstance(fi.node, ast.Module):
            continue
        local_binds = set(fi.params)
        for node in own_nodes(fi.node):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        local_binds.add(t.id)
        for node in own_nodes(fi.node):
            if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                    and node.id in fi.module.mutable_globals
                    and node.id not in local_binds):
                f = index.make_finding(
                    "retrace-hazard", fi, node.lineno,
                    f"traced function reads mutable module state '{node.id}': "
                    "the value is baked in at trace time; later mutations are "
                    "silently ignored by the compiled executable")
                if f:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


def _rule_jit_purity(index: Index) -> List[Finding]:
    out = []
    for q in sorted(index.traced):
        fi = index.functions.get(q)
        if fi is None or isinstance(fi.node, ast.Module):
            continue
        sm = fi.module
        for node in own_nodes(fi.node):
            f = None
            if isinstance(node, ast.Call):
                d = dotted_name(node.func, sm)
                if d in IMPURE_CALLS:
                    f = index.make_finding(
                        "jit-purity", fi, node.lineno,
                        f"{d}() inside a traced function: {IMPURE_CALLS[d]}")
                elif d and (d.startswith("numpy.random.")
                            or (d.startswith("random.")
                                and "random" in sm.imports)):
                    f = index.make_finding(
                        "jit-purity", fi, node.lineno,
                        f"{d}() inside a traced function: host RNG draws once "
                        "at trace time — every compiled call replays the same "
                        "'random' constant; thread jax.random keys instead")
                elif (isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id in sm.mutable_globals
                      and node.func.attr in MUTATOR_METHODS):
                    f = index.make_finding(
                        "jit-purity", fi, node.lineno,
                        f"mutation of module state '{node.func.value.id}' "
                        "inside a traced function: runs once per TRACE, not "
                        "per call — a silent side-effect bug")
            elif isinstance(node, ast.Global):
                f = index.make_finding(
                    "jit-purity", fi, node.lineno,
                    f"global {', '.join(node.names)} inside a traced "
                    "function: rebinding runs once per trace, not per call")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in sm.mutable_globals):
                        f = index.make_finding(
                            "jit-purity", fi, node.lineno,
                            f"item assignment into module state "
                            f"'{t.value.id}' inside a traced function: runs "
                            "once per trace, not per call")
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# numpy-on-tracer
# ---------------------------------------------------------------------------


def _rule_numpy_on_tracer(index: Index) -> List[Finding]:
    out = []
    for q in sorted(index.traced):
        fi = index.functions.get(q)
        if fi is None or isinstance(fi.node, ast.Module):
            continue
        _, tainted = _device_taint(fi, index, seed_params=True)
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func, fi.module)
            if not d or not d.startswith("numpy."):
                continue
            tail = d.split(".", 1)[1]
            if tail.split(".")[0] in NP_METADATA_OK or tail.startswith("random."):
                continue
            if node.args and any(tainted(a) for a in node.args):
                f = index.make_finding(
                    "numpy-on-tracer", fi, node.lineno,
                    f"np.{tail} applied to a traced value: numpy either "
                    "raises TracerArrayConversionError or silently constant-"
                    "folds at trace time; use jnp instead")
                if f:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# monotonic-clock
# ---------------------------------------------------------------------------


_WALL_CLOCKS = {"time.time", "time.time_ns"}


def _rule_monotonic_clock(index: Index) -> List[Finding]:
    """Wall clock in duration/deadline arithmetic: ``time.time()`` (or a name
    assigned from it) fed into +/- or an ordering comparison. The wall clock
    steps under NTP slew/adjustment — elapsed-time math wants
    ``time.monotonic()`` or ``time.perf_counter()``. Value-only uses
    (timestamps recorded into logs/indices) are not flagged."""
    out = []
    for q in sorted(index.functions):
        fi = index.functions[q]
        if isinstance(fi.node, ast.Module):
            continue
        sm = fi.module
        nodes = own_nodes(fi.node)

        wall_names: Set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted_name(node.value.func, sm) in _WALL_CLOCKS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_names.add(t.id)

        def is_wall(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Call) \
                    and dotted_name(expr.func, sm) in _WALL_CLOCKS:
                return True
            return isinstance(expr, ast.Name) and expr.id in wall_names

        for node in nodes:
            hit = False
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Add, ast.Sub)) \
                    and (is_wall(node.left) or is_wall(node.right)):
                hit = True
            elif isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                    for op in node.ops) \
                    and (is_wall(node.left)
                         or any(is_wall(c) for c in node.comparators)):
                hit = True
            if hit:
                f = index.make_finding(
                    "monotonic-clock", fi, node.lineno,
                    "time.time() in duration/deadline arithmetic: the wall "
                    "clock steps under NTP adjustment — use time.monotonic() "
                    "or time.perf_counter() for elapsed time")
                if f:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


def _lockish(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and "lock" in n.attr.lower():
            return True
        if isinstance(n, ast.Name) and "lock" in n.id.lower():
            return True
    return False


def _rule_lock_discipline(index: Index) -> List[Finding]:
    out = []
    for dotted in sorted(index.modules):
        sm = index.modules[dotted]
        if not sm.imports_threading or not sm.mutable_globals:
            continue
        for q in sorted(sm.functions):
            fi = sm.functions[q]
            if isinstance(fi.node, ast.Module):
                continue  # import-time mutation is single-threaded

            globals_decl: Set[str] = set()
            for node in own_nodes(fi.node):
                if isinstance(node, ast.Global):
                    globals_decl.update(node.names)

            def mutation_of(node: ast.AST) -> Optional[str]:
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in sm.mutable_globals \
                        and node.func.attr in MUTATOR_METHODS:
                    return node.func.value.id
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in sm.mutable_globals:
                            return t.value.id
                        if isinstance(t, ast.Name) and t.id in globals_decl \
                                and t.id in sm.mutable_globals:
                            return t.id
                if isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in sm.mutable_globals:
                            return t.value.id
                return None

            def scan(node: ast.AST, lock_depth: int):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    d = lock_depth
                    if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                            _lockish(item.context_expr) for item in child.items):
                        d += 1
                    name = mutation_of(child)
                    if name is not None and lock_depth == 0:
                        f = index.make_finding(
                            "lock-discipline", fi, child.lineno,
                            f"module-level mutable '{name}' mutated without a "
                            "held lock in a threaded module: concurrent "
                            "callers race")
                        if f:
                            out.append(f)
                    scan(child, d)

            scan(fi.node, 0)
    out += _lock_hot_sync_findings(index)
    return out


def _lock_hot_sync_findings(index: Index) -> List[Finding]:
    """Second lock-discipline sub-check, for the serving scheduler's hot
    path: NO HOST SYNC (and no jitted dispatch) while holding a lock. A
    ``with <lock>:`` body that pulls a device value to host — device_get,
    ``.item()``, float/int/bool coercion, np.asarray, block_until_ready —
    or dispatches a jitted callable serializes every other thread behind
    XLA: producers can't even enqueue while the device runs. Admission
    math on host floats under the lock is fine; the device work must
    happen with the lock released (serve/scheduler.py's dispatch shape)."""
    out = []
    for dotted in sorted(index.modules):
        sm = index.modules[dotted]
        if not sm.imports_threading:
            continue
        for q in sorted(sm.functions):
            fi = sm.functions[q]
            if isinstance(fi.node, ast.Module):
                continue
            _, tainted = _device_taint(fi, index, seed_params=False)

            def sync_message(node: ast.AST) -> Optional[str]:
                if not isinstance(node, ast.Call):
                    return None
                d = dotted_name(node.func, sm)
                if d == "jax.device_get":
                    return ("jax.device_get under a held lock: every thread "
                            "queues behind the device→host transfer")
                if d in ("numpy.asarray", "numpy.array", "numpy.copy") \
                        and node.args and any(tainted(a) for a in node.args):
                    return (f"{d.replace('numpy', 'np')} on a device value "
                            "under a held lock: materialization blocks all "
                            "other lock holders")
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr == "block_until_ready":
                        return (".block_until_ready() under a held lock: "
                                "the lock is held for the whole device "
                                "execution")
                    if f.attr == "item" and not node.args \
                            and tainted(f.value):
                        return (".item() on a device value under a held "
                                "lock: synchronous host round-trip while "
                                "others wait")
                    if f.attr in index.jit_names:
                        return ("jitted dispatch under a held lock: XLA "
                                "execution serializes every other thread "
                                "on this lock")
                if isinstance(f, ast.Name):
                    if f.id in ("float", "int", "bool") and node.args \
                            and tainted(node.args[0]):
                        return (f"{f.id}() on a device value under a held "
                                "lock: blocks until the executable "
                                "finishes while others wait")
                    if f.id in index.jit_names \
                            and f.id in sm.global_names:
                        return ("jitted dispatch under a held lock: XLA "
                                "execution serializes every other thread "
                                "on this lock")
                return None

            def scan(node: ast.AST, lock_depth: int):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    d = lock_depth
                    if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                            _lockish(item.context_expr)
                            for item in child.items):
                        d += 1
                    if lock_depth > 0:
                        msg = sync_message(child)
                        if msg:
                            f = index.make_finding("lock-discipline", fi,
                                                   child.lineno, msg)
                            if f:
                                out.append(f)
                    scan(child, d)

            scan(fi.node, 0)
    return out


# ---------------------------------------------------------------------------
# cost-analysis-off-hot-path
# ---------------------------------------------------------------------------

# trace-export entry points (obs/trace_export.py): serializing the whole span
# ring per call — report-time surfaces only
_TRACE_EXPORT_CALLS = {"live_trace", "trace_events"}

# fleet federation entry points (obs/fleet.py): each serializes the whole
# metrics registry + span summary and does store I/O (or scans every
# worker's snapshot) — report-time/boundary surfaces only, never per batch
_FLEET_CALLS = {"publish_snapshot", "collect_snapshots", "serve_collector"}


def _rule_cost_analysis_off_hot_path(index: Index) -> List[Finding]:
    """``cost_analysis()``/``memory_analysis()`` walk the lowered/compiled
    HLO modules host-side — milliseconds per call — the trace-export
    helpers serialize the whole span ring, and the fleet federation
    helpers (obs/fleet.py) additionally do store I/O. None belongs in
    traced bodies (baked in at trace time, re-run per compile) or
    per-batch dispatch code (latency per step). Harvest at compile time
    and render at report time instead (obs/profile.py, obs/trace_export.py,
    obs/fleet.py)."""
    out = []
    for q in sorted(index.traced | index.hot):
        fi = index.functions[q]
        where = "traced" if q in index.traced else "hot-path"
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = None
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                    "cost_analysis", "memory_analysis"):
                f = index.make_finding(
                    "cost-analysis-off-hot-path", fi, node.lineno,
                    f".{node.func.attr}() reachable from {where} code: walks "
                    "the executable's HLO host-side (milliseconds per call); "
                    "harvest once at compile/report time via obs.profile "
                    "instead")
            else:
                d = dotted_name(node.func, fi.module) or ""
                leaf = d.rsplit(".", 1)[-1] if d else (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else "")
                if leaf in _TRACE_EXPORT_CALLS or "trace_export." in d:
                    f = index.make_finding(
                        "cost-analysis-off-hot-path", fi, node.lineno,
                        f"trace export ({leaf or d}) reachable from {where} "
                        "code: serializes the span ring per call; export at "
                        "report time (/debug/trace, DL4J_TPU_SPAN_DUMP) "
                        "instead")
                elif leaf in _FLEET_CALLS:
                    f = index.make_finding(
                        "cost-analysis-off-hot-path", fi, node.lineno,
                        f"fleet federation ({leaf}) reachable from {where} "
                        "code: serializes the metrics registry and does "
                        "store I/O per call; publish at step boundaries / "
                        "collect at report time (obs/fleet.py) instead")
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# tuner-off-hot-path
# ---------------------------------------------------------------------------

# measurement/search entry points of deeplearning4j_tpu.tune: every one
# compiles executables, spawns trial subprocesses, or blocks on timers —
# offline surfaces by contract (tune.maybe_apply, a DB lookup plus env-var
# writes, is the ONLY tune call allowed near the hot path)
_TUNER_MEASURE_CALLS = {
    "run_trial", "run_subprocess_trial", "successive_halving", "tune_model",
}


def _rule_tuner_off_hot_path(index: Index) -> List[Finding]:
    """The auto-tuner's search/trial surfaces measure by running: a trial
    compiles a fresh step executable, a search spawns subprocesses and
    waits on them. Reachable from a traced body that means host calls baked
    in at trace time; reachable from per-batch dispatch code it means
    seconds of stall per step. Tuning is an offline phase — consult its
    RESULTS online via tune.maybe_apply (env-var application at startup),
    never the measurement itself."""
    out = []
    for q in sorted(index.traced | index.hot):
        fi = index.functions[q]
        # the tuner's own modules call these entry points as the offline
        # flow itself (tune_model → halving → subprocess trial → fit);
        # self-calls are the feature, not a hot-path leak
        if "/tune/" in fi.module.path.replace("\\", "/"):
            continue
        where = "traced" if q in index.traced else "hot-path"
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func, fi.module) or ""
            leaf = d.rsplit(".", 1)[-1] if d else (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else node.func.id if isinstance(node.func, ast.Name)
                else "")
            if leaf in _TUNER_MEASURE_CALLS:
                f = index.make_finding(
                    "tuner-off-hot-path", fi, node.lineno,
                    f"tuner measurement ({leaf}) reachable from {where} "
                    "code: trials compile executables and spawn "
                    "subprocesses; tune offline and consult the DB via "
                    "tune.maybe_apply at startup instead")
                if f:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# step-wiring: compiled-step construction outside the step-program module
# ---------------------------------------------------------------------------


def _rule_step_wiring(index: Index) -> List[Finding]:
    """Direct ``jax.jit(..., donate_argnums=...)`` in ``nn/`` or
    ``parallel/`` outside ``nn/step_program.py``. A donated-carry jit IS a
    training/serving step executable, and the framework's step wiring
    (trace sites, AOT warm registration, retrace-guard hookup, the
    grad-accumulation scan) lives in exactly one place — ``StepProgram``.
    Hand-rolled step jits fork that policy a sixth time: they silently miss
    AOT warmup, guard budgets, and the cost-exemplar harvest (ISSUE 13;
    docs/PARALLELISM.md)."""
    out = []
    for q in sorted(index.functions):
        fi = index.functions[q]
        p = "/" + fi.module.relpath.replace("\\", "/")
        if "/nn/" not in p and "/parallel/" not in p:
            continue
        if p.endswith("/step_program.py"):
            continue
        for node in own_nodes(fi.node):
            if not (isinstance(node, ast.Call) and is_jit_call(node, fi.module)):
                continue
            if not any(kw.arg == "donate_argnums" for kw in node.keywords):
                continue
            f = index.make_finding(
                "step-wiring", fi, node.lineno,
                "donated-carry jit built outside nn/step_program.py: step "
                "executables must go through StepProgram so trace/donate/"
                "AOT-warm/retrace-guard policy stays in one place")
            if f:
                out.append(f)
    return out
