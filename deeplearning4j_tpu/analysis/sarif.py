"""SARIF 2.1.0 emission for graftlint findings.

One run, one ``tool.driver`` (graftlint), one rule descriptor per rule that
produced a finding. New findings (beyond the baseline) are ``error`` with
``baselineState: "new"``; grandfathered ones are ``note`` /
``"unchanged"`` so CI annotates only what the current change introduced.
The line-number-free graftlint fingerprint rides in ``partialFingerprints``
under ``graftlint/v1`` — SARIF consumers use it for cross-run matching the
same way ``baseline.json`` does.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Set

from deeplearning4j_tpu.analysis.engine import Finding

__all__ = ["SARIF_VERSION", "SARIF_SCHEMA_URI", "to_sarif"]

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")

# one-line rule descriptions for tool.driver.rules
_RULE_DESCRIPTIONS: Dict[str, str] = {
    "host-sync": "device-to-host pull on the dispatch path",
    "retrace-hazard": "per-call retraces / jit cache misses",
    "jit-purity": "impure value baked in at trace time",
    "numpy-on-tracer": "numpy call on a traced value",
    "lock-discipline": "unguarded shared mutable state",
    "monotonic-clock": "wall clock in duration arithmetic",
    "cost-analysis-off-hot-path": "HLO cost walk per batch",
    "tuner-off-hot-path": "tuner search on the hot path",
    "step-wiring": "donated-carry jit built outside nn/step_program.py",
    "use-after-donate": "read of a buffer donated into a step executable",
    "collective-consistency":
        "rank-divergent or axis-mismatched collective in a mesh step body",
    "durable-store-protocol":
        "non-atomic write on a durable store/checkpoint path",
    "parse-error": "module failed to parse",
}


def _rule_descriptor(rule: str) -> dict:
    desc = _RULE_DESCRIPTIONS.get(rule, rule)
    return {
        "id": rule,
        "shortDescription": {"text": desc},
    }


def _result(f: Finding, is_new: bool) -> dict:
    return {
        "ruleId": f.rule,
        "level": "error" if is_new else "note",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": max(f.line, 1)},
            },
        }],
        "partialFingerprints": {"graftlint/v1": f.fingerprint},
        "baselineState": "new" if is_new else "unchanged",
    }


def to_sarif(findings: Sequence[Finding], new: Iterable[Finding]) -> dict:
    """The full SARIF log dict for one lint run.

    ``findings`` is every finding of the run; ``new`` the subset the
    baseline does not cover (exit-1 drivers)."""
    new_set: Set[Finding] = set(new)
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    rules_seen: List[str] = []
    for f in ordered:
        if f.rule not in rules_seen:
            rules_seen.append(f.rule)
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftlint",
                    "informationUri":
                        "https://github.com/deeplearning4j/deeplearning4j",
                    "rules": [_rule_descriptor(r) for r in rules_seen],
                },
            },
            "results": [_result(f, f in new_set) for f in ordered],
        }],
    }
