"""graftlint: JAX trace-safety static analysis + runtime retrace guard.

Shape-bucketed execution (utils/bucketing.py, docs/PERF.md) only pays off
while nothing silently retraces or drags device arrays back to host
mid-step. The paper's ND4J/libnd4j split made host/device boundaries
explicit; the JAX port hides them — so this package makes them visible:

- ``engine``        AST module index, call graph, jit-reachability sets
- ``rules``         the five rule classes (host-sync, retrace-hazard,
                    jit-purity, numpy-on-tracer, lock-discipline)
- ``lint``          CLI: ``python -m deeplearning4j_tpu.analysis.lint PKG``
                    with a checked-in baseline (``baseline.json``) so new
                    violations fail CI while grandfathered ones are frozen
- ``retrace_guard`` runtime companion: compile-count-vs-bucket-ladder
                    checks on the jitted entry points

This module must stay import-light: it is imported by ``nn.model`` for the
retrace guard and must never initialize a JAX backend at import time.
"""

__all__ = ["engine", "rules", "lint", "retrace_guard"]
