"""graftlint engine: AST module index, call graph, jit-reachability.

Pure static analysis — nothing here imports jax or executes target code.
The engine parses every module of a target package, builds an approximate
intra-package call graph, and classifies functions into the two sets the
rules care about:

- **traced**: functions whose bodies run under ``jax.jit``/``pjit`` tracing
  (functions passed to jit, returned by jit-wrapped factories, decorated
  with jit, plus everything they can reach through the call graph).
  Impurity or numpy-on-tracer here is a silent-wrong-answer or
  trace-failure hazard.
- **hot** (dispatch-adjacent): functions from which a jit call site is
  reachable — the per-step dispatch path around the compiled executables.
  A host sync here (``np.asarray``/``.item()``/``float()`` on a device
  value) stalls the pipeline the shape-bucketing work keeps hot.

On top of the function classification sits an interprocedural,
field-sensitive value layer (:mod:`deeplearning4j_tpu.analysis.dataflow`,
reached lazily through :attr:`Index.dataflow`): def-use chains threaded
across this call graph with ``self.<attr>`` tracked per class. The
distributed-correctness rules (use-after-donate, collective-consistency,
durable-store-protocol — :mod:`analysis.rules_distributed`) run on it.

Resolution is deliberately approximate (bare names in module scope,
``self.``/``cls.`` within same-module classes, ``module.attr`` through
package imports); the baseline + inline suppressions absorb the
imprecision, and any NEW finding fails CI.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "FunctionInfo",
    "Index",
    "SourceModule",
    "dotted_name",
    "own_nodes",
]

# Callables that construct a traced/compiled function from a python one.
JIT_CALLABLES = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "pjit",
}
# Transform wrappers that trace their first argument: jit(value_and_grad(f))
# means f is a traced root too.
TRACING_WRAPPERS = {
    "jax.grad",
    "jax.value_and_grad",
    "jax.vmap",
    "jax.pmap",
    "jax.checkpoint",
    "jax.remat",
    "functools.partial",
}
# Mutable-container constructors for module-level shared-state detection.
MUTABLE_CONSTRUCTORS = {
    "list",
    "dict",
    "set",
    "bytearray",
    "collections.defaultdict",
    "collections.deque",
    "collections.OrderedDict",
    "collections.Counter",
}
MUTATOR_METHODS = {
    "append",
    "appendleft",
    "add",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popleft",
    "popitem",
    "remove",
    "setdefault",
    "sort",
    "reverse",
    "update",
}

SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``fingerprint`` is line-number free (path + rule
    + enclosing function + normalized source text) so the baseline survives
    unrelated edits that shift line numbers."""

    rule: str
    path: str          # posix path relative to the lint root's parent
    line: int
    func: str          # enclosing function qualname ("<module>" at top level)
    message: str
    norm: str = ""     # normalized source line text (fingerprint component)

    @property
    def fingerprint(self) -> str:
        return f"{self.path}::{self.rule}::{self.func}::{self.norm}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.func}: {self.message}"


def own_nodes(fn_node: ast.AST) -> List[ast.AST]:
    """All AST nodes belonging to a function (or module) body EXCLUDING
    nested function/class bodies — those are separate FunctionInfos.
    Lambdas stay included: they execute in the enclosing scope."""
    out: List[ast.AST] = []

    def rec(n: ast.AST):
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            out.append(c)
            rec(c)

    body = getattr(fn_node, "body", [])
    for stmt in body if isinstance(body, list) else []:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        out.append(stmt)
        rec(stmt)
    return out


@dataclass
class FunctionInfo:
    """One function (or the module top-level pseudo-function)."""

    qualname: str                       # "nn.model::MultiLayerNetwork.fit"
    module: "SourceModule"
    node: ast.AST                       # FunctionDef / AsyncFunctionDef / Module
    scope: Tuple[str, ...]              # ("MultiLayerNetwork", "fit")
    class_name: Optional[str] = None    # innermost enclosing class
    params: Set[str] = field(default_factory=set)
    calls: Set[str] = field(default_factory=set)   # resolved callee qualnames

    @property
    def local_name(self) -> str:
        return self.scope[-1] if self.scope else "<module>"

    def local_qual(self) -> str:
        return ".".join(self.scope) if self.scope else "<module>"


class SourceModule:
    """Parsed module + symbol tables."""

    def __init__(self, dotted: str, path: str, relpath: str, source: str):
        self.dotted = dotted            # full dotted name incl. package prefix
        self.path = path
        self.relpath = relpath          # posix, relative to lint root's parent
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.is_package = os.path.basename(path) == "__init__.py"
        self.imports: Dict[str, str] = {}       # local alias -> dotted target
        self.functions: Dict[str, FunctionInfo] = {}   # qualname -> info
        self.classes: Dict[str, Dict[str, str]] = {}   # class -> method -> qualname
        self.mutable_globals: Dict[str, int] = {}      # name -> lineno
        self.global_names: Set[str] = set()            # all top-level bindings
        self.imports_threading = False

    # -- suppression -------------------------------------------------------
    def suppressed(self, line: int, rule: str) -> bool:
        """``# graftlint: disable=<rule>[,<rule>...]`` on the flagged line or
        the line directly above (``all`` disables every rule)."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = SUPPRESS_RE.search(self.lines[ln - 1])
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    if rule in rules or "all" in rules:
                        return True
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return " ".join(self.lines[line - 1].split())
        return ""


def dotted_name(expr: ast.AST, sm: SourceModule) -> Optional[str]:
    """Best-effort dotted path of a Name/Attribute chain, resolving the
    leading name through the module's imports (``jnp.pad`` -> ``jax.numpy.pad``).
    Bare un-imported names resolve to themselves."""
    if isinstance(expr, ast.Name):
        return sm.imports.get(expr.id, expr.id)
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value, sm)
        if base:
            return base + "." + expr.attr
    return None


def is_jit_call(call: ast.Call, sm: SourceModule) -> bool:
    return isinstance(call, ast.Call) and dotted_name(call.func, sm) in JIT_CALLABLES


class Index:
    """Package-wide analysis index.

    ``root`` is the directory of the package to lint (or a single ``.py``
    file). All paths in findings are relative to the root's parent, so
    ``deeplearning4j_tpu/nn/model.py`` reads naturally from the repo root.
    """

    def __init__(self, root: str):
        root = os.path.abspath(root)
        if os.path.isfile(root):
            base = os.path.dirname(root)
            files = [root]
        else:
            base = root
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if not d.startswith(".") and d != "__pycache__")
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        self.root = base
        self.pkg = os.path.basename(base)
        self.modules: Dict[str, SourceModule] = {}
        self.errors: List[Finding] = []
        for path in files:
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            parts = rel[:-3].split("/")          # strip .py
            if parts[-1] == "__init__":
                parts = parts[:-1]
            dotted = ".".join([self.pkg] + parts) if parts else self.pkg
            relout = f"{self.pkg}/{rel}"
            try:
                src = open(path, encoding="utf-8").read()
                sm = SourceModule(dotted, path, relout, src)
            except (SyntaxError, UnicodeDecodeError) as e:
                self.errors.append(Finding(
                    "parse-error", relout, getattr(e, "lineno", 0) or 0,
                    "<module>", f"cannot parse: {e}"))
                continue
            self.modules[dotted] = sm
        self.functions: Dict[str, FunctionInfo] = {}
        for sm in self.modules.values():
            self._scan_module(sm)
        self._build_call_graph()
        self._find_jit()
        self._compute_sets()
        self._dataflow = None

    @property
    def dataflow(self):
        """The interprocedural field-sensitive value layer
        (:class:`analysis.dataflow.Dataflow`), built on first use — the
        classification rules never pay for it."""
        if self._dataflow is None:
            from deeplearning4j_tpu.analysis.dataflow import Dataflow
            self._dataflow = Dataflow(self)
        return self._dataflow

    # -- per-module scan ---------------------------------------------------
    def _scan_module(self, sm: SourceModule):
        for node in ast.walk(sm.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    sm.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
                    if a.asname:
                        sm.imports[a.asname] = a.name
                    if a.name.split(".")[0] == "threading":
                        sm.imports_threading = True
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(sm, node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = f"{base}.{a.name}" if base else a.name
                    sm.imports[a.asname or a.name] = target
                if base == "threading":
                    sm.imports_threading = True

        # module top-level pseudo-function
        mod_fi = FunctionInfo(f"{sm.dotted}::<module>", sm, sm.tree, ())
        sm.functions[mod_fi.qualname] = mod_fi
        self.functions[mod_fi.qualname] = mod_fi

        class_stack: List[str] = []

        def register(node: ast.AST, scope: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    sub = scope + (child.name,)
                    fi = FunctionInfo(
                        f"{sm.dotted}::{'.'.join(sub)}", sm, child, sub,
                        class_name=class_stack[-1] if class_stack else None,
                        params={a.arg for a in (
                            child.args.posonlyargs + child.args.args
                            + child.args.kwonlyargs)}
                        | ({child.args.vararg.arg} if child.args.vararg else set())
                        | ({child.args.kwarg.arg} if child.args.kwarg else set()),
                    )
                    sm.functions[fi.qualname] = fi
                    self.functions[fi.qualname] = fi
                    if class_stack and len(scope) >= 1 and scope[-1] == class_stack[-1]:
                        sm.classes.setdefault(class_stack[-1], {})[child.name] = fi.qualname
                    register(child, sub)
                elif isinstance(child, ast.ClassDef):
                    class_stack.append(child.name)
                    sm.classes.setdefault(child.name, {})
                    register(child, scope + (child.name,))
                    class_stack.pop()
                else:
                    register(child, scope)

        register(sm.tree, ())

        # module-level bindings + mutable containers
        for stmt in sm.tree.body:
            targets: List[ast.expr] = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            for t in targets:
                if isinstance(t, ast.Name):
                    sm.global_names.add(t.id)
                    if self._is_mutable_container(value, sm):
                        sm.mutable_globals[t.id] = stmt.lineno
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                sm.global_names.add(stmt.name)

    def _import_base(self, sm: SourceModule, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        parts = sm.dotted.split(".")
        if not sm.is_package:
            parts = parts[:-1]
        parts = parts[: len(parts) - (node.level - 1)]
        if node.module:
            parts.append(node.module)
        return ".".join(parts)

    @staticmethod
    def _is_mutable_container(value: Optional[ast.AST], sm: SourceModule) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            return dotted_name(value.func, sm) in MUTABLE_CONSTRUCTORS
        return False

    # -- resolution --------------------------------------------------------
    def _resolve_local(self, fi: FunctionInfo, name: str) -> Optional[str]:
        """Resolve a bare name in fi's scope chain: nested defs shadow
        module-level ones."""
        sm = fi.module
        for k in range(len(fi.scope), -1, -1):
            cand = f"{sm.dotted}::{'.'.join(fi.scope[:k] + (name,))}"
            if cand in sm.functions:
                return cand
        return None

    def _resolve_import_target(self, dotted: str) -> Optional[str]:
        """Map an imported dotted path to a function qualname in the index
        (``pkg.utils.bucketing.telemetry`` -> ``pkg.utils.bucketing::telemetry``)."""
        if dotted in self.modules:
            return None  # a module, not a function
        head, _, tail = dotted.rpartition(".")
        if head in self.modules:
            cand = f"{head}::{tail}"
            if cand in self.modules[head].functions:
                return cand
        return None

    def resolve_call(self, fi: FunctionInfo, func_expr: ast.AST) -> List[str]:
        """Resolve a call's target(s) to function qualnames (possibly empty)."""
        sm = fi.module
        if isinstance(func_expr, ast.Name):
            local = self._resolve_local(fi, func_expr.id)
            if local:
                return [local]
            target = sm.imports.get(func_expr.id)
            if target:
                hit = self._resolve_import_target(target)
                if hit:
                    return [hit]
            return []
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                # same class first, then any same-module class (approximates
                # inheritance between classes of one module)
                if fi.class_name and func_expr.attr in sm.classes.get(fi.class_name, {}):
                    return [sm.classes[fi.class_name][func_expr.attr]]
                hits = [methods[func_expr.attr] for methods in sm.classes.values()
                        if func_expr.attr in methods]
                return hits
            d = dotted_name(func_expr, sm)
            if d:
                hit = self._resolve_import_target(d)
                if hit:
                    return [hit]
        return []

    # -- call graph --------------------------------------------------------
    def _build_call_graph(self):
        self.edges: Dict[str, Set[str]] = {q: set() for q in self.functions}
        for q, fi in self.functions.items():
            # defining a nested function wires an edge to it (closures are
            # near-always invoked or returned by their parent)
            prefix = q + "."
            for other in fi.module.functions:
                if other.startswith(prefix) and "." not in other[len(prefix):]:
                    self.edges[q].add(other)
            for node in own_nodes(fi.node):
                if isinstance(node, ast.Call):
                    for callee in self.resolve_call(fi, node.func):
                        if callee != q:
                            self.edges[q].add(callee)
                            fi.calls.add(callee)
        self.redges: Dict[str, Set[str]] = {q: set() for q in self.functions}
        for q, outs in self.edges.items():
            for o in outs:
                self.redges.setdefault(o, set()).add(q)

    # -- jit discovery -----------------------------------------------------
    def _find_jit(self):
        """Fixpoint over: jit factories (functions returning jit-wrapped
        callables), jit names (attrs/globals holding jitted callables), jit
        sites (functions that construct or dispatch them), traced roots."""
        self.jit_factories: Set[str] = set()
        self.jit_names: Set[str] = set()
        self.jit_sites: Set[str] = set()
        self.traced_roots: Set[str] = set()
        self.jit_call_nodes: List[Tuple[FunctionInfo, ast.Call]] = []

        for fi in self.functions.values():
            for node in own_nodes(fi.node):
                if isinstance(node, ast.Call) and is_jit_call(node, fi.module):
                    self.jit_call_nodes.append((fi, node))

        # decorated functions are traced roots AND their def site dispatches
        for fi in self.functions.values():
            for dec in getattr(fi.node, "decorator_list", []):
                d = (dotted_name(dec, fi.module) if not isinstance(dec, ast.Call)
                     else dotted_name(dec.func, fi.module))
                if d in JIT_CALLABLES:
                    self.traced_roots.add(fi.qualname)
                    self.jit_sites.add(fi.qualname)
                elif isinstance(dec, ast.Call) and d == "functools.partial" and dec.args:
                    if dotted_name(dec.args[0], fi.module) in JIT_CALLABLES:
                        self.traced_roots.add(fi.qualname)
                        self.jit_sites.add(fi.qualname)

        for _ in range(4):  # small fixpoint: factory -> name -> factory chains
            changed = False
            for fi in self.functions.values():
                for node in own_nodes(fi.node):
                    if isinstance(node, ast.Return) and node.value is not None:
                        if self._produces_jit(fi, node.value):
                            if fi.qualname not in self.jit_factories:
                                self.jit_factories.add(fi.qualname)
                                changed = True
                    elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                        value = node.value
                        if value is None or not self._produces_jit(fi, value):
                            continue
                        targets = (node.targets if isinstance(node, ast.Assign)
                                   else [node.target])
                        for t in targets:
                            name = self._binding_name(t, fi)
                            if name and name not in self.jit_names:
                                self.jit_names.add(name)
                                changed = True
            if not changed:
                break

        # jit sites: construct a jit, or read a jit-holding name/attr
        for fi in self.functions.values():
            for node in own_nodes(fi.node):
                if isinstance(node, ast.Call) and is_jit_call(node, fi.module):
                    self.jit_sites.add(fi.qualname)
                elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
                    if node.attr in self.jit_names:
                        self.jit_sites.add(fi.qualname)
                elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    if node.id in self.jit_names and node.id in fi.module.global_names:
                        self.jit_sites.add(fi.qualname)

        # traced roots from jit call arguments
        for fi, call in self.jit_call_nodes:
            arg = None
            if call.args:
                arg = call.args[0]
            else:
                for kw in call.keywords:
                    if kw.arg in ("fun", "f"):
                        arg = kw.value
            if arg is not None:
                self.traced_roots.update(self._roots_from(fi, arg, depth=0))

    def _binding_name(self, target: ast.AST, fi: FunctionInfo) -> Optional[str]:
        if isinstance(target, ast.Attribute):
            return target.attr
        if isinstance(target, ast.Subscript):
            return self._binding_name(target.value, fi)
        if isinstance(target, ast.Name) and not fi.scope:  # module level
            return target.id
        return None

    def _produces_jit(self, fi: FunctionInfo, expr: ast.AST) -> bool:
        """Does evaluating ``expr`` plausibly yield a jitted callable?"""
        if isinstance(expr, ast.Call):
            if is_jit_call(expr, fi.module):
                return True
            return any(c in self.jit_factories
                       for c in self.resolve_call(fi, expr.func))
        if isinstance(expr, ast.Attribute):
            return expr.attr in self.jit_names
        if isinstance(expr, ast.Name):
            return expr.id in self.jit_names and expr.id in fi.module.global_names
        return False

    def _roots_from(self, fi: FunctionInfo, expr: ast.AST, depth: int) -> Set[str]:
        """Traced functions named by a jit-call argument."""
        if depth > 3:
            return set()
        out: Set[str] = set()
        if isinstance(expr, (ast.Name, ast.Attribute)):
            if isinstance(expr, ast.Name):
                hit = self._resolve_local(fi, expr.id)
                if hit:
                    out.add(hit)
            else:
                out.update(self.resolve_call(fi, expr))
        elif isinstance(expr, ast.Call):
            d = dotted_name(expr.func, fi.module)
            if d in TRACING_WRAPPERS and expr.args:
                out.update(self._roots_from(fi, expr.args[0], depth + 1))
            else:
                # factory call: the functions its returns name are the roots
                for callee in self.resolve_call(fi, expr.func):
                    cfi = self.functions.get(callee)
                    if cfi is None:
                        continue
                    for node in own_nodes(cfi.node):
                        if isinstance(node, ast.Return) and node.value is not None:
                            out.update(self._roots_from(cfi, node.value, depth + 1))
        return out

    # -- reachability ------------------------------------------------------
    def _reach(self, seeds: Iterable[str], edges: Dict[str, Set[str]]) -> Set[str]:
        seen = set(seeds)
        frontier = list(seen)
        while frontier:
            nxt = []
            for q in frontier:
                for o in edges.get(q, ()):
                    if o not in seen:
                        seen.add(o)
                        nxt.append(o)
            frontier = nxt
        return seen

    def _compute_sets(self):
        # traced: forward closure of traced roots
        self.traced: Set[str] = self._reach(self.traced_roots, self.edges)
        # hot: everything that can REACH a jit site (reverse closure)
        self.hot: Set[str] = self._reach(self.jit_sites, self.redges)
        # device sources: functions that (transitively) call jax.device_put —
        # their results live on device even without a jit in sight
        put_seeds = set()
        for fi in self.functions.values():
            for node in own_nodes(fi.node):
                if isinstance(node, ast.Call):
                    if dotted_name(node.func, fi.module) == "jax.device_put":
                        put_seeds.add(fi.qualname)
        self.device_sources: Set[str] = self._reach(put_seeds, self.redges)

    # -- convenience -------------------------------------------------------
    def make_finding(self, rule: str, fi: FunctionInfo, line: int,
                     message: str) -> Optional[Finding]:
        """Build a Finding unless suppressed inline."""
        sm = fi.module
        if sm.suppressed(line, rule):
            return None
        return Finding(rule, sm.relpath, line, fi.local_qual(), message,
                       norm=sm.line_text(line))
