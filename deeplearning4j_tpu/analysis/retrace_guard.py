"""Runtime retrace guard: compile counts must match the bucket ladder.

Static analysis (``rules.py``) proves the code can't easily regress into
per-call retraces; this module proves the *process* didn't. The bucketing
telemetry (``utils/bucketing.py``) already counts actual traces — jitted
bodies call ``record_trace`` which runs once per compile — and bucket hits
per dispatch. The ladder therefore predicts an upper bound: a jitted entry
point should compile **at most once per distinct bucket its traffic used**.
More compiles than buckets means something varied beyond the leading dim —
an unpadded shape, a non-hashable static argument, a fresh jit wrapper.

Checks are opt-in (telemetry is process-global, so unrelated models sharing
a site would trip false alarms in ordinary runs):

- ``DL4J_TPU_RETRACE_GUARD=1``  enable checks; violations warn once per site
- ``DL4J_TPU_STRICT_RETRACE=1`` enable checks; violations raise RetraceError

``nn.model``/``nn.graph`` call ``check_if_enabled(...)`` after each jitted
dispatch; ``RetraceGuard`` wraps standalone functions with jit + telemetry +
the same bound check. Nothing here imports jax at module import time.
"""

from __future__ import annotations

import os
import threading
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.utils import bucketing

__all__ = [
    "GuardReport",
    "RetraceError",
    "RetraceGuard",
    "RetraceWarning",
    "aot_warmed_buckets",
    "check",
    "check_if_enabled",
    "enabled",
    "predicted_compiles",
    "register_aot_warmed",
    "reset_aot_warmed",
    "reset_warnings",
    "strict",
]


class RetraceError(RuntimeError):
    """A jitted site compiled more often than its bucket ladder predicts."""


class RetraceWarning(UserWarning):
    """Non-strict flavor of :class:`RetraceError`."""


def strict() -> bool:
    return os.environ.get("DL4J_TPU_STRICT_RETRACE", "0") != "0"


def enabled() -> bool:
    return strict() or os.environ.get("DL4J_TPU_RETRACE_GUARD", "0") != "0"


@dataclass(frozen=True)
class GuardReport:
    """Outcome of one bound check. ``predicted`` is None when the site has
    no recorded bucket traffic yet (nothing to bound against)."""

    site: str
    compiles: int
    predicted: Optional[int]
    ok: bool


# one warning per site per process; tests reset between cases
_warned: Set[str] = set()
_warned_lock = threading.Lock()


def reset_warnings() -> None:
    with _warned_lock:
        _warned.clear()


# AOT cross-registration (nn/aot.py): buckets compiled ahead of time have a
# legitimate trace each even before any traffic hits them, so the predicted
# bound below unions warmed buckets with observed traffic. Conversely the AOT
# warmup enumerates the SAME ladder the guard bounds against
# (``aot.reachable_buckets``), so the two subsystems cross-check: AOT warming
# a bucket the guard never sees traffic for is accounted, and traffic in a
# bucket AOT failed to enumerate shows up as a lazy compile within the bound.
_aot_warmed: dict = {}
_aot_lock = threading.Lock()


def register_aot_warmed(site: str, buckets) -> None:
    """Record that ``site`` was AOT-compiled for ``buckets`` (leading-dim
    rungs), extending the predicted compile bound accordingly."""
    with _aot_lock:
        _aot_warmed.setdefault(site, set()).update(int(b) for b in buckets)


def aot_warmed_buckets(site: str) -> frozenset:
    with _aot_lock:
        return frozenset(_aot_warmed.get(site, ()))


def reset_aot_warmed() -> None:
    with _aot_lock:
        _aot_warmed.clear()


def predicted_compiles(site: str, hits_site: Optional[str] = None) -> Optional[int]:
    """Ladder-predicted compile bound for ``site``: the number of distinct
    buckets its traffic hit, unioned with buckets AOT-warmed for the site
    (``register_aot_warmed``). Trace and hit counters may live under
    different site names (e.g. traces at ``mln.step``, hits at ``mln.fit``)
    — ``hits_site`` names the hit counter when they differ."""
    used = set(bucketing.telemetry().buckets_used(hits_site or site))
    used |= aot_warmed_buckets(site)
    if hits_site:
        used |= aot_warmed_buckets(hits_site)
    return len(used) if used else None


def check(site: str, hits_site: Optional[str] = None,
          extra_allowed: int = 0) -> GuardReport:
    """Compare observed compiles at ``site`` against the ladder bound.
    Violations raise :class:`RetraceError` under ``DL4J_TPU_STRICT_RETRACE=1``
    and otherwise emit one :class:`RetraceWarning` per site."""
    tel = bucketing.telemetry()
    compiles = tel.compiles(site)
    predicted = predicted_compiles(site, hits_site)
    ok = predicted is None or compiles <= predicted + extra_allowed
    report = GuardReport(site, compiles, predicted, ok)
    if not ok:
        from deeplearning4j_tpu import obs

        buckets = tel.buckets_used(hits_site or site)
        obs.event("retrace_guard", site=site, compiles=compiles,
                  predicted=predicted, buckets=sorted(buckets))
        msg = (
            f"retrace guard: site '{site}' compiled {compiles}x but its "
            f"traffic used only {predicted} bucket(s) {list(buckets)}"
            + (f" (+{extra_allowed} allowed)" if extra_allowed else "")
            + " — something retraces beyond the bucket ladder (unpadded "
            "shape, non-hashable static arg, or a fresh jit wrapper per call)"
        )
        if strict():
            raise RetraceError(msg)
        with _warned_lock:
            first = site not in _warned
            _warned.add(site)
        if first:
            warnings.warn(msg, RetraceWarning, stacklevel=2)
    return report


def check_if_enabled(site: str, hits_site: Optional[str] = None,
                     extra_allowed: int = 0) -> Optional[GuardReport]:
    """No-op unless the guard env knobs are set — the hook jitted dispatch
    paths call unconditionally."""
    if not enabled():
        return None
    return check(site, hits_site, extra_allowed=extra_allowed)


def _leading_dim(args: Sequence[Any], skip: Tuple[int, ...]) -> Optional[int]:
    for i, a in enumerate(args):
        if i in skip:
            continue
        shape = getattr(a, "shape", None)
        if shape is not None and len(shape) >= 1:
            return int(shape[0])
    return None


class RetraceGuard:
    """jit + telemetry + bound check for a standalone function.

    ``RetraceGuard(fn, site)`` behaves like ``jax.jit(fn)`` except that every
    compile records a trace event and every call records a bucket hit (by the
    first non-static argument's leading dim, rounded up the ladder), then the
    compile count is checked against the ladder bound via
    ``check_if_enabled``. jax is imported lazily on first call."""

    def __init__(self, fn: Callable, site: str,
                 static_argnums: Sequence[int] = (),
                 ladder: Optional[bucketing.BucketLadder] = None,
                 **jit_kwargs: Any):
        self._fn = fn
        self.site = site
        self._static = tuple(static_argnums)
        self._ladder = ladder
        self._jit_kwargs = jit_kwargs
        self._jitted: Optional[Callable] = None

    def _build(self) -> Callable:
        import jax

        fn, site, static = self._fn, self.site, self._static

        def traced(*args, **kwargs):
            lead = _leading_dim(args, static)
            bucketing.telemetry().record_trace(
                site, (lead,) if lead is not None else ())
            return fn(*args, **kwargs)

        # the wrapper forwards the caller's literal spec verbatim
        return jax.jit(traced, static_argnums=self._static,  # graftlint: disable=retrace-hazard
                       **self._jit_kwargs)

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            self._jitted = self._build()
        n = _leading_dim(args, self._static)
        if n is not None:
            bucketing.telemetry().record_hit(
                self.site, n, bucketing.bucket_size(n, self._ladder))
        out = self._jitted(*args, **kwargs)
        check_if_enabled(self.site)
        return out

    @property
    def report(self) -> GuardReport:
        """Current bound check without warning/raising."""
        tel = bucketing.telemetry()
        compiles = tel.compiles(self.site)
        predicted = predicted_compiles(self.site)
        ok = predicted is None or compiles <= predicted
        return GuardReport(self.site, compiles, predicted, ok)
