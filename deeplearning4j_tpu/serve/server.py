"""HTTP/JSON inference server over the continuous-batching scheduler.

Routes (stdlib ThreadingHTTPServer — one OS thread per connection, which
is exactly what the coalescing scheduler wants: concurrent blocked
``submit`` calls ARE the batch):

- ``POST /v1/models/<name>:predict`` with ``{"inputs": [[...], ...],
  "deadline_ms": 50}`` → ``{"outputs": [...], "rows": n}``. Status codes
  carry the overload semantics end to end: 200 served, 400 malformed
  payload, 404 unknown model, **429** shed by queue backpressure (with
  ``Retry-After``), **503** shed because the deadline is infeasible or
  already expired;
- ``POST /v1/models/<name>:generate`` with ``{"prompt": [token ids],
  "max_tokens": 32, "deadline_ms": 30000, "eos": 2}`` → a CHUNKED
  (HTTP/1.1 ``Transfer-Encoding: chunked``) ``application/x-ndjson``
  stream: one ``{"token": id, "i": n}`` line per generated token, flushed
  the moment the decode engine emits it (token-level streaming — TTFT is
  prefill latency, not whole-response latency), then a terminal
  ``{"done": true, "reason": ..., "tokens": n, "ttft_ms": ...}`` line.
  Arrival-time sheds keep the predict() status semantics (429/503) since
  no bytes have streamed yet; a MID-STREAM shed (deadline repriced per
  remaining token budget) arrives as the terminal line's
  ``reason == "shed:deadline"`` — the status line already said 200;
- ``POST /v1/search`` with ``{"index": name?, "queries": [[...], ...],
  "k": 10, "nprobe": 8?, "tier": "ivf"?, "deadline_ms": 50?}`` →
  ``{"ids": [...], "distances": [...], "tier": ..., "rows": n}`` — the
  device-resident ANN tier (search/, docs/SEARCH.md) behind the same
  deadline admission + signature-coalescing scheduler, same status codes;
- ``POST /knn`` / ``POST /knnnew`` / ``GET /status`` — the legacy
  NearestNeighborsServer wire contract (clustering/server.py is now a thin
  shim over this stack), resolved against the sole registered index;
- ``GET /v1/models`` → per-model pool stats (queue depth, batches, warm
  metadata);
- ``GET /healthz``, ``GET /metrics`` — from serve/httpcommon.py; /metrics
  exposes the whole obs registry including ``dl4j_requests_total``,
  ``dl4j_shed_total`` and ``dl4j_slo_burn_rate`` for the serve routes.

SLO route labels are collapsed to ``serve.<name>:http`` / ``/v1/models`` /
``/metrics`` … so label cardinality stays bounded by the model count, not
the URL space.

The launcher (``python -m deeplearning4j_tpu.serve``) builds the registry
from ``name=path`` arguments — each runs the import → AOT-warm → serve
pipeline (serve/registry.py) BEFORE the socket binds, so a server that
answers its port never compiles on the request path.
"""

from __future__ import annotations

import json
import re
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.serve import httpcommon
from deeplearning4j_tpu.serve.admission import ServeConfig
from deeplearning4j_tpu.serve.registry import ModelRegistry
from deeplearning4j_tpu.serve.scheduler import ShedError

__all__ = ["InferenceServer"]

_PREDICT_RE = re.compile(r"^/v1/models/([\w.\-]+):predict$")
_GENERATE_RE = re.compile(r"^/v1/models/([\w.\-]+):generate$")


class InferenceServer:
    """``InferenceServer(registry).start(port)`` — see module docstring."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 config: Optional[ServeConfig] = None):
        self.registry = registry or ModelRegistry(config=config)
        self._httpd = None
        self._thread = None
        self.port: Optional[int] = None
        self._inflight = httpcommon.InFlight()

    # -- lifecycle ---------------------------------------------------------

    def start(self, port: int = 0) -> "InferenceServer":
        outer = self

        class Handler(httpcommon.ObservedHandler):
            inflight = outer._inflight
            # chunked transfer encoding (the streaming generate route) is
            # an HTTP/1.1 feature; Content-Length replies are unaffected
            protocol_version = "HTTP/1.1"

            def slo_route(self, path: str) -> str:
                m = _PREDICT_RE.match(path)
                if m:
                    return f"serve.{m.group(1)}:http"
                m = _GENERATE_RE.match(path)
                if m:
                    return f"generate.{m.group(1)}:http"
                if path in ("/v1/search", "/knn", "/knnnew"):
                    # the index name lives in the body, not the URL; one
                    # bounded label covers the whole search surface
                    return "search:http"
                return path

            def handle_get(self) -> int:
                path = urlparse(self.path).path
                if path == "/v1/models":
                    return self.send_json(200,
                                          {"models": outer.registry.describe()})
                if path == "/status":
                    worker = outer.registry.searcher(None)
                    if worker is None:
                        return self.send_json(
                            404, {"error": "no index served"})
                    ix = worker.index
                    return self.send_json(200, {
                        "ok": True,
                        "points": int(ix.n + ix._pending_n),
                        "dim": int(ix.config.dim)})
                self.send_response(404)
                self.end_headers()
                return 404

            # -- streaming generate ----------------------------------------

            def _chunk(self, data: bytes) -> None:
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data)
                self.wfile.write(b"\r\n")

            def handle_generate(self, name: str) -> int:
                gen = outer.registry.generator(name)
                if gen is None:
                    return self.send_json(
                        404, {"error": f"model {name!r} not served for "
                              f"generation", "served": outer.registry.names()})
                try:
                    payload = self.read_json()
                    prompt = [int(t) for t in payload["prompt"]]
                    max_new = payload.get("max_tokens")
                    eos = payload.get("eos")
                    eos = None if eos is None else int(eos)
                    deadline_ms = payload.get("deadline_ms")
                    deadline_s = (None if deadline_ms is None
                                  else float(deadline_ms) / 1e3)
                    if deadline_s is not None and deadline_s <= 0:
                        raise ValueError("deadline_ms must be > 0")
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                try:
                    stream = gen.submit(prompt, max_new=max_new, eos=eos,
                                        deadline_s=deadline_s)
                except ShedError as e:
                    body = {"error": str(e), "shed": e.reason}
                    if e.http_status == 429:
                        return self.send_json(429, body,
                                              headers=(("Retry-After", "1"),))
                    return self.send_json(503, body)
                except ValueError as e:
                    return self.send_json(400, {"error": str(e)})
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                if self.trace is not None:
                    self.send_header("traceparent", self.trace.header())
                self.end_headers()
                try:
                    for i, tok in enumerate(stream):
                        self._chunk(json.dumps(
                            {"token": int(tok), "i": i}).encode() + b"\n")
                        self.wfile.flush()
                    tail = {"done": True, "reason": stream.finish_reason,
                            "tokens": len(stream.tokens)}
                    if stream.ttft_s is not None:
                        tail["ttft_ms"] = round(stream.ttft_s * 1e3, 3)
                except Exception as e:
                    tail = {"done": True, "reason": "error", "error": str(e)}
                if self.trace is not None:
                    # end-to-end correlation over chunked HTTP: the terminal
                    # line names the trace so a client can resolve its
                    # request in the merged Perfetto timeline
                    tail["request_id"] = self.trace.trace_id
                try:
                    self._chunk(json.dumps(tail).encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream; engine already done
                return 200

            # -- vector search ---------------------------------------------

            def _send_shed(self, e: ShedError) -> int:
                body = {"error": str(e), "shed": e.reason}
                if e.http_status == 429:
                    return self.send_json(429, body,
                                          headers=(("Retry-After", "1"),))
                return self.send_json(503, body)

            def handle_search(self) -> int:
                try:
                    payload = self.read_json()
                    name = payload.get("index")
                    queries = np.asarray(payload["queries"], np.float32)
                    k = int(payload.get("k", 10))
                    nprobe = payload.get("nprobe")
                    nprobe = None if nprobe is None else int(nprobe)
                    tier = payload.get("tier")
                    deadline_ms = payload.get("deadline_ms")
                    deadline_s = (None if deadline_ms is None
                                  else float(deadline_ms) / 1e3)
                    if deadline_s is not None and deadline_s <= 0:
                        raise ValueError("deadline_ms must be > 0")
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                worker = outer.registry.searcher(name)
                if worker is None:
                    return self.send_json(
                        404, {"error": f"index {name!r} not served",
                              "served": outer.registry.names()})
                try:
                    ids, dists, tier_used = worker.submit(
                        queries, k=k, nprobe=nprobe, tier=tier,
                        deadline_s=deadline_s)
                except ShedError as e:
                    return self._send_shed(e)
                except ValueError as e:
                    return self.send_json(400, {"error": str(e)})
                except Exception as e:
                    return self.send_json(500, {"error": str(e)})
                body = {
                    "ids": ids.tolist(),
                    "distances": dists.tolist(),
                    "tier": tier_used,
                    "rows": int(len(ids)),
                }
                if self.trace is not None:
                    body["request_id"] = self.trace.trace_id
                return self.send_json(200, body)

            def handle_knn(self, by_vector: bool) -> int:
                """Legacy NearestNeighborsServer contract: /knn looks up an
                indexed row (excluding itself), /knnnew a raw vector; both
                answer ``{"results": [{"index", "distance"}, ...]}`` and map
                malformed requests to the legacy 400 ``{"error"}`` shape.
                Sheds keep the unified 429/503 semantics (the legacy server
                had no admission at all)."""
                worker = outer.registry.searcher(None)
                if worker is None:
                    return self.send_json(404, {"error": "no index served"})
                ix = worker.index
                try:
                    payload = self.read_json()
                    k = int(payload.get("k", 5))
                    if k < 1:
                        raise ValueError(f"k must be >= 1, got {k}")
                    if by_vector:
                        vec = np.asarray(
                            payload["ndarray"], np.float32).reshape(1, -1)
                        exclude = -1
                        want = min(k, ix.config.max_k)
                    else:
                        row = int(np.asarray(payload["ndarray"]).reshape(()))
                        if not 0 <= row < ix.n:
                            raise ValueError(f"index {row} out of range")
                        vec = ix._vectors[row][None]
                        exclude = row
                        # one extra so dropping the query row still fills k
                        want = min(k + 1, ix.config.max_k)
                except ShedError:
                    raise
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                try:
                    ids, dists, _ = worker.submit(vec, k=want)
                except ShedError as e:
                    return self._send_shed(e)
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                results = [
                    {"index": int(i), "distance": float(d)}
                    for i, d in zip(ids[0], dists[0])
                    if i >= 0 and i != exclude][:k]
                return self.send_json(200, {"results": results})

            def handle_post(self) -> int:
                path = urlparse(self.path).path
                if path == "/v1/search":
                    return self.handle_search()
                if path in ("/knn", "/knnnew"):
                    return self.handle_knn(by_vector=(path == "/knnnew"))
                g = _GENERATE_RE.match(urlparse(self.path).path)
                if g:
                    return self.handle_generate(g.group(1))
                m = _PREDICT_RE.match(urlparse(self.path).path)
                if not m:
                    return self.send_json(404, {"error": "no such route"})
                worker = outer.registry.worker(m.group(1))
                if worker is None:
                    return self.send_json(
                        404, {"error": f"model {m.group(1)!r} not served",
                              "served": outer.registry.names()})
                try:
                    payload = self.read_json()
                    x = np.asarray(payload["inputs"], dtype=np.float32)
                    deadline_ms = payload.get("deadline_ms")
                    deadline_s = (None if deadline_ms is None
                                  else float(deadline_ms) / 1e3)
                    if deadline_s is not None and deadline_s <= 0:
                        raise ValueError("deadline_ms must be > 0")
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                try:
                    out = worker.submit(x, deadline_s=deadline_s)
                except ShedError as e:
                    body = {"error": str(e), "shed": e.reason}
                    if e.http_status == 429:
                        # closed-loop clients back off for one deadline's
                        # worth of queue drain rather than hammering
                        return self.send_json(
                            429, body,
                            headers=(("Retry-After", "1"),))
                    return self.send_json(503, body)
                except ValueError as e:
                    return self.send_json(400, {"error": str(e)})
                except Exception as e:
                    return self.send_json(500, {"error": str(e)})
                body = {
                    "outputs": np.asarray(out).tolist(),
                    "rows": int(len(out)),
                }
                if self.trace is not None:
                    body["request_id"] = self.trace.trace_id
                return self.send_json(200, body)

        self._httpd, self._thread, self.port = httpcommon.start_server(
            Handler, port)
        obs.event("serve_started", port=self.port,
                  models=",".join(self.registry.names()))
        return self

    def stop(self, shutdown_registry: bool = True) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread:
                self._thread.join(timeout=10)
                self._thread = None
        if shutdown_registry:
            self.registry.shutdown()
