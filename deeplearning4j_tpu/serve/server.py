"""HTTP/JSON inference server over the continuous-batching scheduler.

Routes (stdlib ThreadingHTTPServer — one OS thread per connection, which
is exactly what the coalescing scheduler wants: concurrent blocked
``submit`` calls ARE the batch):

- ``POST /v1/models/<name>:predict`` with ``{"inputs": [[...], ...],
  "deadline_ms": 50}`` → ``{"outputs": [...], "rows": n}``. Status codes
  carry the overload semantics end to end: 200 served, 400 malformed
  payload, 404 unknown model, **429** shed by queue backpressure (with
  ``Retry-After``), **503** shed because the deadline is infeasible or
  already expired;
- ``POST /v1/models/<name>:generate`` with ``{"prompt": [token ids],
  "max_tokens": 32, "deadline_ms": 30000, "eos": 2}`` → a CHUNKED
  (HTTP/1.1 ``Transfer-Encoding: chunked``) ``application/x-ndjson``
  stream: one ``{"token": id, "i": n}`` line per generated token, flushed
  the moment the decode engine emits it (token-level streaming — TTFT is
  prefill latency, not whole-response latency), then a terminal
  ``{"done": true, "reason": ..., "tokens": n, "ttft_ms": ...}`` line.
  Arrival-time sheds keep the predict() status semantics (429/503) since
  no bytes have streamed yet; a MID-STREAM shed (deadline repriced per
  remaining token budget) arrives as the terminal line's
  ``reason == "shed:deadline"`` — the status line already said 200;
- ``GET /v1/models`` → per-model pool stats (queue depth, batches, warm
  metadata);
- ``GET /healthz``, ``GET /metrics`` — from serve/httpcommon.py; /metrics
  exposes the whole obs registry including ``dl4j_requests_total``,
  ``dl4j_shed_total`` and ``dl4j_slo_burn_rate`` for the serve routes.

SLO route labels are collapsed to ``serve.<name>:http`` / ``/v1/models`` /
``/metrics`` … so label cardinality stays bounded by the model count, not
the URL space.

The launcher (``python -m deeplearning4j_tpu.serve``) builds the registry
from ``name=path`` arguments — each runs the import → AOT-warm → serve
pipeline (serve/registry.py) BEFORE the socket binds, so a server that
answers its port never compiles on the request path.
"""

from __future__ import annotations

import json
import re
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.serve import httpcommon
from deeplearning4j_tpu.serve.admission import ServeConfig
from deeplearning4j_tpu.serve.registry import ModelRegistry
from deeplearning4j_tpu.serve.scheduler import ShedError

__all__ = ["InferenceServer"]

_PREDICT_RE = re.compile(r"^/v1/models/([\w.\-]+):predict$")
_GENERATE_RE = re.compile(r"^/v1/models/([\w.\-]+):generate$")


class InferenceServer:
    """``InferenceServer(registry).start(port)`` — see module docstring."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 config: Optional[ServeConfig] = None):
        self.registry = registry or ModelRegistry(config=config)
        self._httpd = None
        self._thread = None
        self.port: Optional[int] = None
        self._inflight = httpcommon.InFlight()

    # -- lifecycle ---------------------------------------------------------

    def start(self, port: int = 0) -> "InferenceServer":
        outer = self

        class Handler(httpcommon.ObservedHandler):
            inflight = outer._inflight
            # chunked transfer encoding (the streaming generate route) is
            # an HTTP/1.1 feature; Content-Length replies are unaffected
            protocol_version = "HTTP/1.1"

            def slo_route(self, path: str) -> str:
                m = _PREDICT_RE.match(path)
                if m:
                    return f"serve.{m.group(1)}:http"
                m = _GENERATE_RE.match(path)
                return f"generate.{m.group(1)}:http" if m else path

            def handle_get(self) -> int:
                if urlparse(self.path).path == "/v1/models":
                    return self.send_json(200,
                                          {"models": outer.registry.describe()})
                self.send_response(404)
                self.end_headers()
                return 404

            # -- streaming generate ----------------------------------------

            def _chunk(self, data: bytes) -> None:
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data)
                self.wfile.write(b"\r\n")

            def handle_generate(self, name: str) -> int:
                gen = outer.registry.generator(name)
                if gen is None:
                    return self.send_json(
                        404, {"error": f"model {name!r} not served for "
                              f"generation", "served": outer.registry.names()})
                try:
                    payload = self.read_json()
                    prompt = [int(t) for t in payload["prompt"]]
                    max_new = payload.get("max_tokens")
                    eos = payload.get("eos")
                    eos = None if eos is None else int(eos)
                    deadline_ms = payload.get("deadline_ms")
                    deadline_s = (None if deadline_ms is None
                                  else float(deadline_ms) / 1e3)
                    if deadline_s is not None and deadline_s <= 0:
                        raise ValueError("deadline_ms must be > 0")
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                try:
                    stream = gen.submit(prompt, max_new=max_new, eos=eos,
                                        deadline_s=deadline_s)
                except ShedError as e:
                    body = {"error": str(e), "shed": e.reason}
                    if e.http_status == 429:
                        return self.send_json(429, body,
                                              headers=(("Retry-After", "1"),))
                    return self.send_json(503, body)
                except ValueError as e:
                    return self.send_json(400, {"error": str(e)})
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for i, tok in enumerate(stream):
                        self._chunk(json.dumps(
                            {"token": int(tok), "i": i}).encode() + b"\n")
                        self.wfile.flush()
                    tail = {"done": True, "reason": stream.finish_reason,
                            "tokens": len(stream.tokens)}
                    if stream.ttft_s is not None:
                        tail["ttft_ms"] = round(stream.ttft_s * 1e3, 3)
                except Exception as e:
                    tail = {"done": True, "reason": "error", "error": str(e)}
                try:
                    self._chunk(json.dumps(tail).encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream; engine already done
                return 200

            def handle_post(self) -> int:
                g = _GENERATE_RE.match(urlparse(self.path).path)
                if g:
                    return self.handle_generate(g.group(1))
                m = _PREDICT_RE.match(urlparse(self.path).path)
                if not m:
                    return self.send_json(404, {"error": "no such route"})
                worker = outer.registry.worker(m.group(1))
                if worker is None:
                    return self.send_json(
                        404, {"error": f"model {m.group(1)!r} not served",
                              "served": outer.registry.names()})
                try:
                    payload = self.read_json()
                    x = np.asarray(payload["inputs"], dtype=np.float32)
                    deadline_ms = payload.get("deadline_ms")
                    deadline_s = (None if deadline_ms is None
                                  else float(deadline_ms) / 1e3)
                    if deadline_s is not None and deadline_s <= 0:
                        raise ValueError("deadline_ms must be > 0")
                except Exception as e:
                    return self.send_json(400, {"error": str(e)})
                try:
                    out = worker.submit(x, deadline_s=deadline_s)
                except ShedError as e:
                    body = {"error": str(e), "shed": e.reason}
                    if e.http_status == 429:
                        # closed-loop clients back off for one deadline's
                        # worth of queue drain rather than hammering
                        return self.send_json(
                            429, body,
                            headers=(("Retry-After", "1"),))
                    return self.send_json(503, body)
                except ValueError as e:
                    return self.send_json(400, {"error": str(e)})
                except Exception as e:
                    return self.send_json(500, {"error": str(e)})
                return self.send_json(200, {
                    "outputs": np.asarray(out).tolist(),
                    "rows": int(len(out)),
                })

        self._httpd, self._thread, self.port = httpcommon.start_server(
            Handler, port)
        obs.event("serve_started", port=self.port,
                  models=",".join(self.registry.names()))
        return self

    def stop(self, shutdown_registry: bool = True) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread:
                self._thread.join(timeout=10)
                self._thread = None
        if shutdown_registry:
            self.registry.shutdown()
