"""``python -m deeplearning4j_tpu.serve name=path [name=path ...]``

Stand up the inference server: import each model (Keras ``.h5`` or DL4J
``.zip``, format auto-detected), run the AOT warm pipeline (restoring /
writing ``<path>.aotbundle`` sidecars where persistence is validated), and
serve them all from one port. The socket binds only after every model is
warm — time-to-first-request never pays an XLA compile.

Options: ``--port N`` (default 8000; 0 = OS-assigned, printed on stdout).
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.serve",
        description="continuous-batching inference server")
    ap.add_argument("models", nargs="+", metavar="name=path",
                    help="model to serve: name=path/to/model.h5|.zip")
    ap.add_argument("--port", type=int, default=8000)
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.serve import InferenceServer, ModelRegistry

    registry = ModelRegistry()
    for spec in args.models:
        name, _, path = spec.partition("=")
        if not path:
            ap.error(f"expected name=path, got {spec!r}")
        print(f"loading {name} from {path} ...", flush=True)
        registry.load(name, path)
    srv = InferenceServer(registry).start(port=args.port)
    print(f"serving {', '.join(registry.names())} on "
          f"http://127.0.0.1:{srv.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
