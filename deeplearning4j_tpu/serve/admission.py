"""Deadline-aware admission control: the serving tier's batching math.

μ-cuDNN (arXiv:1804.04806) picks per-layer micro-batch sizes by MEASUREMENT
against a time budget instead of by convention. Applied to request serving,
the same principle becomes: the batch a request coalesces into — and hence
the shape-ladder bucket it dispatches on — is chosen against the tightest
admitted DEADLINE using measured per-bucket execution latency, not by a
fixed drain tick or a fixed batch size.

Three separable pieces live here, all host-side float arithmetic (no jax,
no device sync — the scheduler calls these while holding its admission
lock, and graftlint's lock-discipline rule enforces that nothing here may
stall it):

- :class:`ServeConfig` — the ``DL4J_TPU_SERVE_*`` knob surface, read once
  per construction so launchers/tests control it per instance.
- :class:`LatencyModel` — measured per-(model, bucket) execution latency.
  Observations land in the ``dl4j_serve_exec_seconds{model,bucket}``
  histogram (P² streaming quantiles, obs/metrics.py) so the estimate is
  the same number operators see at /metrics; an estimate is only trusted
  for shedding once a bucket has ``min_samples`` observations (until then
  the system admits optimistically — never shed on a guess).
- :class:`AdmissionController` — the pure decisions:

  * ``infeasible(rows, deadline, now)``     → shed-on-arrival check
  * ``admit_more(rows, add, tightest, now)``→ coalesce one more request?
  * ``can_wait(rows, tightest, now)``       → keep the batch open one more
    wait quantum hoping for coalescing, or dispatch now?

  The admission loop built from these admits-until-deadline-margin: a
  forming batch keeps absorbing compatible requests while the NEXT bucket's
  measured latency still fits inside the tightest admitted deadline minus
  the safety margin — which is exactly "pick the bucket that maximizes
  goodput within the tightest admitted deadline", since every admitted
  request adds real rows and the loop stops at the last bucket whose
  estimate is feasible.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.utils import bucketing

__all__ = ["AdmissionController", "GenerateConfig", "LatencyModel",
           "ServeConfig", "TokenAdmission"]


@dataclass(frozen=True)
class ServeConfig:
    """The ``DL4J_TPU_SERVE_*`` knob surface (docs/SERVING.md)."""

    max_batch: int = 32          # coalescing cap == AOT warm target (rows)
    queue_limit: int = 256       # per-model queue bound; beyond it -> 429
    margin_s: float = 0.005      # deadline safety margin
    max_wait_s: float = 0.002    # max time a batch stays open for coalescing
    wait_quantum_s: float = 0.0002   # admission loop poll interval
    default_deadline_s: float = 0.25  # deadline for requests that carry none
    min_samples: int = 3         # measurements before an estimate can shed
    workers: int = 1             # dispatcher threads per model pool

    @staticmethod
    def from_env() -> "ServeConfig":
        env = os.environ.get
        # default deadline follows the SLO latency objective: a request
        # with no explicit deadline is late exactly when the SLO says so
        default_ms = env("DL4J_TPU_SERVE_DEFAULT_DEADLINE_MS",
                         env("DL4J_TPU_SLO_LATENCY_MS", "250"))
        return ServeConfig(
            max_batch=int(env("DL4J_TPU_SERVE_MAX_BATCH", "32")),
            queue_limit=int(env("DL4J_TPU_SERVE_QUEUE", "256")),
            margin_s=float(env("DL4J_TPU_SERVE_MARGIN_MS", "5")) / 1e3,
            max_wait_s=float(env("DL4J_TPU_SERVE_WAIT_MS", "2")) / 1e3,
            wait_quantum_s=float(env("DL4J_TPU_SERVE_WAIT_QUANTUM_MS",
                                     "0.2")) / 1e3,
            default_deadline_s=float(default_ms) / 1e3,
            min_samples=int(env("DL4J_TPU_SERVE_MIN_SAMPLES", "3")),
            workers=int(env("DL4J_TPU_SERVE_WORKERS", "1")),
        )


class LatencyModel:
    """Measured per-(model, bucket) execution latency.

    ``observe`` records one dispatch's wall time into the shared
    ``dl4j_serve_exec_seconds`` histogram and a small internal ledger;
    ``estimate`` answers "how long will a batch on this bucket take" from
    the P² p95 of those observations — pessimistic enough that a feasible
    verdict usually holds, cheap enough (dict lookups under the family
    lock) for the admission loop.

    Estimates interpolate: an unmeasured bucket borrows the nearest
    measured bucket's latency scaled by the row ratio (compute scales at
    most linearly in padded rows for row-independent inference). A model
    with NO trusted measurement returns None — callers must admit
    optimistically, because shedding on a guess would reject traffic the
    hardware could have served.
    """

    def __init__(self, registry=None, min_samples: int = 3):
        from deeplearning4j_tpu.obs import metrics as _metrics

        reg = registry if registry is not None else _metrics.registry()
        self._hist = reg.histogram(
            "dl4j_serve_exec_seconds",
            "serving dispatch execution latency by model and bucket "
            "(source of the admission loop's feasibility estimates)",
            ("model", "bucket"))
        self.min_samples = min_samples
        self._lock = threading.Lock()
        # (model, bucket) -> count; cheap trusted-set membership without
        # walking the histogram family on every estimate
        self._counts: Dict[Tuple[str, int], int] = {}

    def observe(self, model: str, bucket: int, seconds: float):
        self._hist.observe(seconds, model=model, bucket=bucket)
        with self._lock:
            self._counts[(model, int(bucket))] = \
                self._counts.get((model, int(bucket)), 0) + 1

    def samples(self, model: str, bucket: int) -> int:
        with self._lock:
            return self._counts.get((model, int(bucket)), 0)

    def estimate(self, model: str, bucket: int) -> Optional[float]:
        """p95 execution-latency estimate for ``bucket``, or None when the
        model has no bucket with ``min_samples`` measurements yet."""
        bucket = int(bucket)
        with self._lock:
            trusted = [b for (m, b), c in self._counts.items()
                       if m == model and c >= self.min_samples]
        if not trusted:
            return None
        nearest = min(trusted, key=lambda b: (abs(b - bucket), b))
        s = self._hist.summary(model=model, bucket=nearest)
        if s is None:  # registry reset between observe and estimate
            return None
        p95 = float(s["p95"])
        if nearest == bucket:
            return p95
        # linear row scaling, never below the measured floor: padded-row
        # inference work grows at most linearly, fixed overheads don't shrink
        return p95 * max(1.0, bucket / nearest)

    def clear(self):
        with self._lock:
            self._counts.clear()


class AdmissionController:
    """Pure deadline-admission decisions over a :class:`LatencyModel`.

    Every method takes ``now`` explicitly (``time.perf_counter()`` scale,
    same clock as the deadlines) so the math is deterministic under test.
    """

    def __init__(self, latency: LatencyModel, config: ServeConfig,
                 ladder: Optional[bucketing.BucketLadder] = None):
        self.latency = latency
        self.config = config
        self.ladder = ladder or bucketing.ladder_from_env()

    def _bucket(self, rows: int) -> int:
        return (self.ladder.bucket(rows)
                if bucketing.bucketing_enabled() else rows)

    def eta(self, model: str, rows: int, now: float) -> Optional[float]:
        """Estimated completion time for dispatching ``rows`` now, or None
        when unmeasured (optimistic)."""
        est = self.latency.estimate(model, self._bucket(rows))
        return None if est is None else now + est

    def infeasible(self, model: str, rows: int, deadline: float,
                   now: float) -> bool:
        """Shed-on-arrival: even dispatched IMMEDIATELY and ALONE, the
        request's measured bucket latency overruns its deadline (minus the
        safety margin). Unmeasured models are never infeasible."""
        eta = self.eta(model, rows, now)
        return eta is not None and eta + self.config.margin_s > deadline

    def admit_more(self, model: str, rows: int, add_rows: int,
                   tightest: float, now: float) -> bool:
        """Coalesce one more request (``add_rows`` rows, deadline already
        folded into ``tightest``) into a forming batch of ``rows``?

        Admit while the GROWN batch's bucket still meets the tightest
        admitted deadline with margin. Every admission adds real rows to
        one dispatch, so stopping at the last feasible bucket is the
        goodput-maximizing choice within that deadline."""
        total = rows + add_rows
        if total > self.config.max_batch:
            return False
        eta = self.eta(model, total, now)
        return eta is None or eta + self.config.margin_s <= tightest

    def can_wait(self, model: str, rows: int, tightest: float,
                 now: float) -> bool:
        """Keep the batch open one more wait quantum hoping more requests
        arrive (admit-until-deadline-margin, NOT a fixed drain tick)?
        Only while the current bucket dispatched AFTER the wait would still
        make the tightest deadline; an unmeasured model relies on the
        scheduler's ``max_wait_s`` cap alone."""
        if rows >= self.config.max_batch:
            return False
        after_wait = now + self.config.wait_quantum_s
        eta = self.eta(model, rows, after_wait)
        return eta is None or eta + self.config.margin_s <= tightest


@dataclass(frozen=True)
class GenerateConfig:
    """The generative-serving knob surface (``DL4J_TPU_GEN_*`` plus the
    two tuner-searched decode knobs, docs/SERVING.md). Read AFTER
    ``tune.maybe_apply(model, "serve")`` so ``DL4J_TPU_TUNE`` selections
    for ``kv_page_tokens``/``decode_batch_max`` land here."""

    decode_batch_max: int = 8    # token-level continuous-batch width cap
    kv_page_tokens: int = 64     # KV-cache page size (tokens per page)
    prefill_chunk: int = 64      # max prompt tokens per prefill dispatch
    max_new_default: int = 64    # max_tokens for requests that carry none
    queue_limit: int = 64        # waiting-stream bound; beyond it -> 429
    margin_s: float = 0.005      # deadline safety margin (shared with serve)
    default_deadline_s: float = 30.0  # generous: streams run many tokens
    min_samples: int = 3         # measurements before an estimate can shed
    paged: bool = True           # paged pool vs contiguous strips

    @staticmethod
    def from_env() -> "GenerateConfig":
        env = os.environ.get
        return GenerateConfig(
            decode_batch_max=int(env("DL4J_TPU_DECODE_BATCH_MAX", "8")),
            kv_page_tokens=int(env("DL4J_TPU_KV_PAGE_TOKENS", "64")),
            prefill_chunk=int(env("DL4J_TPU_PREFILL_CHUNK", "64")),
            max_new_default=int(env("DL4J_TPU_GEN_MAX_NEW", "64")),
            queue_limit=int(env("DL4J_TPU_GEN_QUEUE", "64")),
            margin_s=float(env("DL4J_TPU_SERVE_MARGIN_MS", "5")) / 1e3,
            default_deadline_s=float(env("DL4J_TPU_GEN_DEADLINE_MS",
                                         "30000")) / 1e3,
            min_samples=int(env("DL4J_TPU_SERVE_MIN_SAMPLES", "3")),
            paged=env("DL4J_TPU_KV_PAGED", "1") != "0",
        )


class TokenAdmission:
    """Deadline decisions repriced per remaining TOKEN budget.

    A fixed-shape request has one dispatch between admission and response;
    a token stream has ``prefill + max_new`` of them, so its feasibility
    must be repriced as the budget drains: a stream that was feasible at
    admission becomes worth shedding mid-flight the moment
    ``now + remaining_tokens x measured_ITL`` overruns its deadline —
    every further step it runs steals decode-batch slots from streams
    that can still finish.

    Latency ledger keys (one :class:`LatencyModel`, two logical sites):
    ``{model}:decode`` bucketed by batch rows (the per-token step) and
    ``{model}:prefill`` bucketed by chunk width. Both unmeasured → admit
    optimistically, never shed on a guess (LatencyModel discipline).
    """

    def __init__(self, latency: LatencyModel, config: GenerateConfig,
                 ladder: Optional[bucketing.BucketLadder] = None):
        self.latency = latency
        self.config = config
        self.ladder = ladder or bucketing.ladder_from_env()

    def _bucket(self, n: int) -> int:
        return self.ladder.bucket(n) if bucketing.bucketing_enabled() else n

    def itl(self, model: str, batch_rows: int) -> Optional[float]:
        """Measured per-token step latency at the given batch width."""
        return self.latency.estimate(f"{model}:decode",
                                     self._bucket(max(1, batch_rows)))

    def prefill_eta(self, model: str, prompt_len: int) -> Optional[float]:
        """Measured time to prefill a prompt, summed over chunk dispatches."""
        chunk = self.config.prefill_chunk
        total, n = 0.0, 0
        while n < prompt_len:
            c = min(chunk, prompt_len - n)
            est = self.latency.estimate(f"{model}:prefill", self._bucket(c))
            if est is None:
                return None
            total += est
            n += c
        return total

    def infeasible(self, model: str, prompt_len: int, max_new: int,
                   deadline: float, now: float) -> bool:
        """Shed-on-arrival: even admitted IMMEDIATELY, the stream's full
        token budget (prefill + max_new decode steps at measured ITL)
        overruns its deadline. Unmeasured components price as zero —
        admit optimistically."""
        pre = self.prefill_eta(model, prompt_len) or 0.0
        itl = self.itl(model, 1) or 0.0
        if pre == 0.0 and itl == 0.0:
            return False
        eta = now + pre + max_new * itl
        return eta + self.config.margin_s > deadline

    def should_shed(self, model: str, remaining: int, deadline: float,
                    now: float, batch_rows: int = 1) -> bool:
        """Mid-stream repricing at a token boundary: shed when the
        REMAINING budget at the currently measured ITL can no longer make
        the deadline. Never sheds without a trusted measurement."""
        if remaining <= 0:
            return now > deadline
        itl = self.itl(model, batch_rows)
        if itl is None:
            return now + self.config.margin_s > deadline
        return now + remaining * itl + self.config.margin_s > deadline
