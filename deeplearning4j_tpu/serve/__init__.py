"""serve/ — continuous-batching inference tier.

The serving counterpart to the training runtime: an HTTP/JSON front
(serve/server.py) over per-model continuous-batching pools
(serve/scheduler.py) whose coalescing decisions are deadline admission
math over measured per-bucket latency (serve/admission.py), fed by the
import → AOT-warm → serve registry pipeline (serve/registry.py). Shared
HTTP plumbing (SLO envelope, /metrics, /healthz) lives in
serve/httpcommon.py and is reused by ui/server.py.

Quick start::

    from deeplearning4j_tpu import serve

    registry = serve.ModelRegistry()
    registry.load("mnist", "model.h5")          # import + AOT warm
    srv = serve.InferenceServer(registry).start(port=8000)
    # POST /v1/models/mnist:predict {"inputs": [...], "deadline_ms": 50}

or from a shell: ``python -m deeplearning4j_tpu.serve mnist=model.h5``.

Token-level generative serving rides the same tier:
``registry.register_generate(name, model)`` AOT-warms the bucketed
KV-cache decode engine (nn/decode.py) behind a
:class:`~.scheduler.GenerateWorker`, streamed over HTTP as
``POST /v1/models/<name>:generate`` (chunked NDJSON).

So does vector search: ``registry.register_index(name, index)`` puts a
device-resident ANN index (search/, docs/SEARCH.md) behind a
signature-coalescing :class:`~.scheduler.SearchWorker`, served as
``POST /v1/search`` plus the legacy ``/knn`` / ``/knnnew`` / ``/status``
contract; search adds ``DL4J_TPU_SEARCH_BATCH_MAX``,
``DL4J_TPU_IVF_NLIST``, ``DL4J_TPU_IVF_NPROBE`` (build-time knobs).

Knobs: ``DL4J_TPU_SERVE_MAX_BATCH``, ``DL4J_TPU_SERVE_QUEUE``,
``DL4J_TPU_SERVE_MARGIN_MS``, ``DL4J_TPU_SERVE_WAIT_MS``,
``DL4J_TPU_SERVE_WAIT_QUANTUM_MS``, ``DL4J_TPU_SERVE_DEFAULT_DEADLINE_MS``,
``DL4J_TPU_SERVE_MIN_SAMPLES``, ``DL4J_TPU_SERVE_WORKERS``; generation adds
``DL4J_TPU_DECODE_BATCH_MAX``, ``DL4J_TPU_KV_PAGE_TOKENS``,
``DL4J_TPU_KV_PAGED``, ``DL4J_TPU_PREFILL_CHUNK``, ``DL4J_TPU_GEN_MAX_NEW``,
``DL4J_TPU_GEN_QUEUE``, ``DL4J_TPU_GEN_DEADLINE_MS`` — docs/SERVING.md.
"""

from deeplearning4j_tpu.serve.admission import (
    AdmissionController, GenerateConfig, LatencyModel, ServeConfig,
    TokenAdmission)
from deeplearning4j_tpu.serve.registry import ModelRegistry
from deeplearning4j_tpu.serve.scheduler import (
    GenerateStream, GenerateWorker, ModelWorker, SearchWorker, ShedError)
from deeplearning4j_tpu.serve.server import InferenceServer

__all__ = [
    "AdmissionController",
    "GenerateConfig",
    "GenerateStream",
    "GenerateWorker",
    "InferenceServer",
    "LatencyModel",
    "ModelRegistry",
    "ModelWorker",
    "SearchWorker",
    "ServeConfig",
    "ShedError",
    "TokenAdmission",
]
