"""Continuous-batching scheduler: per-model queues, coalescing dispatchers.

One :class:`ModelWorker` per served model owns a bounded request queue and
a small pool of dispatcher threads. Each dispatcher runs the continuous-
batching loop:

1. **Pop** the oldest request (the batch seed).
2. **Admit** — coalesce compatible queued requests into the forming batch
   while the grown batch's bucket still meets the tightest admitted
   deadline with margin (:class:`~.admission.AdmissionController`), waiting
   in sub-millisecond quanta for more traffic only while that same check
   says the wait is affordable (admit-until-deadline-margin, not a fixed
   drain tick).
3. **Dispatch** OUTSIDE the admission lock: concatenate rows, let
   ``model.output`` pad up the shared bucket ladder (one executable per
   bucket; AOT-warmed at registration so the request path never compiles),
   slice results back per request, measure the execution latency into the
   :class:`~.admission.LatencyModel`.

Overload protection is fail-fast, never queue-unboundedly:

- **Backpressure** — a full queue sheds at submit (→ HTTP 429).
- **Deadline shedding** — a request whose measured bucket latency cannot
  meet its deadline is shed at arrival, and one that expires while queued
  is shed at assembly instead of wasting a dispatch (→ HTTP 503).

Both paths record ``dl4j_requests_total{status="shed"}`` +
``dl4j_shed_total{reason}`` and burn SLO error budget (obs/slo.py), so the
burn-rate gauge reacts to overload exactly as it does to latency misses.

Lock discipline (enforced by graftlint's lock-discipline rule): everything
under ``self._cond`` is host-side queue/float arithmetic — the device
dispatch, the result materialization, and the per-request fan-out all
happen with the lock released, so producers are never stalled behind XLA.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.obs import fleet
from deeplearning4j_tpu.serve.admission import (
    AdmissionController, GenerateConfig, LatencyModel, ServeConfig,
    TokenAdmission)
from deeplearning4j_tpu.utils import bucketing

__all__ = ["GenerateStream", "GenerateWorker", "ModelWorker", "SearchWorker",
           "ShedError", "ServeConfig"]


class ShedError(RuntimeError):
    """A request the serving tier refused to run. ``reason`` is
    ``backpressure`` (queue full → HTTP 429), ``deadline`` (cannot meet the
    request's deadline → HTTP 503) or ``shutdown`` (→ HTTP 503)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason

    @property
    def http_status(self) -> int:
        return 429 if self.reason == "backpressure" else 503


def _trace_attrs(batch) -> Dict[str, str]:
    """Span attrs linking one coalesced dispatch back to the trace ids of
    every request in it (deduped, submit order) — the join key between a
    front-door ``http.request`` span and the batch that served it."""
    ids: List[str] = []
    for r in batch:
        t = getattr(r, "trace", None)
        if t is not None and t.trace_id not in ids:
            ids.append(t.trace_id)
    return {"traces": ",".join(ids)} if ids else {}


class _Req:
    __slots__ = ("x", "rows", "deadline", "arrival", "event", "result",
                 "error", "trace")

    def __init__(self, x, deadline: float, arrival: float):
        self.x = x
        self.rows = len(x)
        self.deadline = deadline
        self.arrival = arrival
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        # the submitter's trace context: the dispatcher thread runs on its
        # own stack, so the HTTP front door's traceparent must ride the
        # request object to reach the dispatch span
        self.trace: Optional[fleet.TraceContext] = None


class ModelWorker:
    """Deadline-aware continuous-batching front for ONE model.

    ``submit`` blocks the calling thread until its rows come back (or
    raises :class:`ShedError`); the dispatcher pool coalesces concurrent
    callers into bucket-ladder batches. ``latency`` may be shared across
    workers (the registry shares one :class:`LatencyModel` so /metrics has
    a single family) — estimates are keyed per model name.
    """

    def __init__(self, name: str, model, config: Optional[ServeConfig] = None,
                 latency: Optional[LatencyModel] = None,
                 ladder: Optional[bucketing.BucketLadder] = None):
        self.name = name
        self.model = model
        self.config = config or ServeConfig.from_env()
        self.route = f"serve.{name}"
        self.latency = latency or LatencyModel(
            min_samples=self.config.min_samples)
        self.admission = AdmissionController(self.latency, self.config,
                                             ladder=ladder)
        self._cond = threading.Condition()
        self._q: List[_Req] = []
        self._stop = False
        self._shed_seen: set = set()
        self._batches = obs.counter(
            "dl4j_serve_batches_total",
            "coalesced dispatches by model", ("model",))
        self._batch_rows = obs.histogram(
            "dl4j_serve_batch_rows",
            "real rows per coalesced dispatch (fill, before bucket padding)",
            ("model",))
        self._depth = obs.gauge(
            "dl4j_serve_queue_depth",
            "requests waiting in the per-model serving queue", ("model",))
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"serve-{name}-{i}")
            for i in range(max(1, self.config.workers))]
        for t in self._threads:
            t.start()

    # -- producer side -----------------------------------------------------

    def submit(self, x, deadline_s: Optional[float] = None) -> np.ndarray:
        """Serve one request of ``len(x)`` rows. ``deadline_s`` is relative
        to now (defaults to ``ServeConfig.default_deadline_s``); the call
        blocks until the rows are served, or raises :class:`ShedError` /
        the model's own failure."""
        x = np.asarray(x)
        if x.ndim < 1 or len(x) == 0:
            raise ValueError("request must carry at least one row")
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        r = _Req(x, now + deadline_s, now)
        r.trace = fleet.current_trace()
        # arrival feasibility BEFORE touching the queue: a request whose
        # bucket measurably overruns its own deadline wastes queue space
        # and device time — reject it while it is cheapest (503 semantics)
        if self.admission.infeasible(self.name, r.rows, r.deadline, now):
            self._shed(r, "deadline")
            raise ShedError("deadline",
                            f"{self.name}: measured bucket latency cannot "
                            f"meet deadline {deadline_s * 1e3:.1f}ms")
        with self._cond:
            if self._stop:
                raise ShedError("shutdown", f"{self.name}: worker shut down")
            if len(self._q) >= self.config.queue_limit:
                depth = len(self._q)
                shed = True
            else:
                shed = False
                self._q.append(r)
                depth = len(self._q)
                self._cond.notify()
        self._depth.set(depth, model=self.name)
        if shed:
            self._shed(r, "backpressure")
            raise ShedError("backpressure",
                            f"{self.name}: queue full ({depth} waiting)")
        r.event.wait()
        if r.error is not None:
            raise r.error
        return r.result

    # -- shed accounting ---------------------------------------------------

    def _shed(self, r: _Req, reason: str):
        obs.observe_shed(self.route, reason=reason)
        if reason not in self._shed_seen:  # first occurrence: one event
            self._shed_seen.add(reason)
            obs.event("serve_shed", model=self.name, reason=reason,
                      rows=int(r.rows))

    # -- dispatcher side ---------------------------------------------------

    def _worker_loop(self):
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if self._stop and not self._q:
                    return
                first = self._q.pop(0)
                depth = len(self._q)
            self._depth.set(depth, model=self.name)
            batch = self._assemble(first)
            if batch:
                self._dispatch(batch)

    def _assemble(self, first: _Req) -> List[_Req]:
        """The admission loop: grow [first] while the admission controller
        approves, shedding queued requests that expired. Returns the batch
        to dispatch (possibly empty if every candidate expired)."""
        cfg = self.config
        batch: List[_Req] = []
        rows = 0
        tightest = float("inf")
        opened = time.perf_counter()
        candidate: Optional[_Req] = first
        while True:
            now = time.perf_counter()
            if candidate is not None:
                merged = min(tightest, candidate.deadline)
                if now + cfg.margin_s > candidate.deadline:
                    # expired while queued: a late response is a failed
                    # response that also ate device time — shed instead
                    self._shed(candidate, "deadline")
                    candidate.error = ShedError(
                        "deadline", f"{self.name}: deadline expired in queue")
                    candidate.event.set()
                elif not batch or self.admission.admit_more(
                        self.name, rows, candidate.rows, merged, now):
                    batch.append(candidate)
                    rows += candidate.rows
                    tightest = merged
                else:
                    # would overrun the tightest admitted deadline (or the
                    # batch cap): leave it at the queue head for the next
                    # batch — this batch dispatches on the last bucket that
                    # stays feasible
                    with self._cond:
                        self._q.insert(0, candidate)
                    break
                candidate = None
                continue
            if rows >= cfg.max_batch:
                break
            with self._cond:
                if self._q:
                    candidate = self._q.pop(0)
                    continue
            if self._stop or now - opened >= cfg.max_wait_s:
                break
            if batch and not self.admission.can_wait(
                    self.name, rows, tightest, now):
                break
            time.sleep(cfg.wait_quantum_s)
        return batch

    def _dispatch(self, batch: List[_Req]):
        total = sum(r.rows for r in batch)
        bucket = (bucketing.bucket_size(total)
                  if bucketing.bucketing_enabled() else total)
        bucketing.telemetry().record_hit(self.route, total, bucket)
        try:
            with obs.span("serve.dispatch", model=self.name,
                          rows=int(total), **_trace_attrs(batch)):
                xs = (batch[0].x if len(batch) == 1
                      else np.concatenate([r.x for r in batch], axis=0))
                t0 = time.perf_counter()
                # model.output pads up the shared ladder itself, so this
                # dispatch hits the SAME executable (and AOT warm entry) a
                # direct caller would — the basis of coalescing bit-exactness
                out = np.asarray(self.model.output(xs))
                dt = time.perf_counter() - t0
            self.latency.observe(self.name, bucket, dt)
            self._batches.inc(model=self.name)
            self._batch_rows.observe(total, model=self.name)
            done = time.perf_counter()
            ofs = 0
            for r in batch:
                r.result = out[ofs:ofs + r.rows]
                ofs += r.rows
                r.event.set()
                obs.observe_request(self.route, done - r.arrival,
                                    status="ok")
        except Exception as e:  # propagate to every waiter, keep serving
            done = time.perf_counter()
            for r in batch:
                r.error = e
                r.event.set()
                obs.observe_request(self.route, done - r.arrival,
                                    status="error", error=True)

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._cond:
            depth = len(self._q)
        return {
            "model": self.name,
            "queue_depth": depth,
            "queue_limit": self.config.queue_limit,
            "max_batch": self.config.max_batch,
            "batches": int(self._batches.value(model=self.name)),
            "workers": len(self._threads),
        }

    def shutdown(self, timeout_s: float = 5.0):
        with self._cond:
            self._stop = True
            stranded = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in stranded:
            r.error = ShedError("shutdown", f"{self.name}: worker shut down")
            r.event.set()
        for t in self._threads:
            t.join(timeout=timeout_s)


# ---------------------------------------------------------------------------
# Vector search: signature-compatible query coalescing
# ---------------------------------------------------------------------------


class _SearchReq:
    __slots__ = ("q", "rows", "k", "kb", "nprobe", "tier", "deadline",
                 "arrival", "event", "result", "error", "trace")

    def __init__(self, q, k: int, kb: int, nprobe: int, tier: str,
                 deadline: float, arrival: float):
        self.q = q
        self.rows = len(q)
        self.k = k
        self.kb = kb
        self.nprobe = nprobe
        self.tier = tier
        self.deadline = deadline
        self.arrival = arrival
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        self.trace: Optional[fleet.TraceContext] = None

    @property
    def key(self):
        """Coalescing compatibility: only requests that would dispatch the
        SAME executable signature (tier, padded k, nprobe) may share a
        batch — so a coalesced response is bit-exact vs serving alone."""
        return (self.tier, self.kb, self.nprobe)


class SearchWorker:
    """Deadline-aware continuous batching for ONE
    :class:`~deeplearning4j_tpu.search.index.VectorIndex`.

    Same shape as :class:`ModelWorker` with one twist: the admit loop only
    coalesces *signature-compatible* requests (same tier / k-bucket /
    nprobe — see :meth:`_SearchReq.key`); incompatible requests stay queued
    for the next batch rather than forcing a second executable into this
    dispatch. Latency estimates key per ``{index}:{tier}`` because the
    tiers sit at very different points on the latency/recall curve.
    """

    def __init__(self, name: str, index,
                 config: Optional[ServeConfig] = None,
                 latency: Optional[LatencyModel] = None,
                 ladder: Optional[bucketing.BucketLadder] = None):
        import dataclasses

        self.name = name
        self.index = index
        base = config or ServeConfig.from_env()
        # the index's own coalescing cap (search_batch_max knob) bounds the
        # batch — it is what the signature grid was warmed for
        self.config = dataclasses.replace(
            base, max_batch=int(index.config.batch_max))
        self.route = f"search.{name}"
        self.latency = latency or LatencyModel(
            min_samples=self.config.min_samples)
        self.admission = AdmissionController(self.latency, self.config,
                                             ladder=ladder)
        self._cond = threading.Condition()
        self._q: List[_SearchReq] = []
        self._stop = False
        self._shed_seen: set = set()
        self._batches = obs.counter(
            "dl4j_serve_batches_total",
            "coalesced dispatches by model", ("model",))
        self._batch_rows = obs.histogram(
            "dl4j_serve_batch_rows",
            "real rows per coalesced dispatch (fill, before bucket padding)",
            ("model",))
        self._depth = obs.gauge(
            "dl4j_serve_queue_depth",
            "requests waiting in the per-model serving queue", ("model",))
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"search-{name}-{i}")
            for i in range(max(1, self.config.workers))]
        for t in self._threads:
            t.start()

    # -- producer side -----------------------------------------------------

    def submit(self, queries, k: int = 10, nprobe: Optional[int] = None,
               tier: Optional[str] = None,
               deadline_s: Optional[float] = None):
        """Top-k search for ``queries`` ([B, dim]); blocks until served.
        Returns ``(ids, distances, tier)``. Raises ``ValueError`` on a
        malformed request (HTTP 400) or :class:`ShedError` (429/503)."""
        ix = self.index
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != ix.config.dim:
            raise ValueError(
                f"queries must be [B, {ix.config.dim}], got "
                f"{np.asarray(queries).shape}")
        if q.shape[0] == 0:
            raise ValueError("request must carry at least one query")
        if q.shape[0] > self.config.max_batch:
            raise ValueError(
                f"request of {q.shape[0]} queries exceeds search_batch_max "
                f"{self.config.max_batch}; split the batch client-side")
        if not 1 <= int(k) <= ix.config.max_k:
            raise ValueError(
                f"k must be in [1, {ix.config.max_k}], got {k}")
        tier = tier or ix.default_tier
        if tier not in ix.available_tiers():
            raise ValueError(f"tier {tier!r} not available; index has "
                             f"{ix.available_tiers()}")
        kb = min((c for c in ix.k_choices if c >= int(k)),
                 default=ix.k_choices[-1])
        p = ix._resolve_nprobe(nprobe) if tier != "exact" else 0
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        r = _SearchReq(q, int(k), kb, p, tier, now + deadline_s, now)
        r.trace = fleet.current_trace()
        lkey = f"{self.name}:{tier}"
        if self.admission.infeasible(lkey, r.rows, r.deadline, now):
            self._shed(r, "deadline")
            raise ShedError("deadline",
                            f"{self.name}: measured {tier} latency cannot "
                            f"meet deadline {deadline_s * 1e3:.1f}ms")
        with self._cond:
            if self._stop:
                raise ShedError("shutdown", f"{self.name}: worker shut down")
            if len(self._q) >= self.config.queue_limit:
                depth = len(self._q)
                shed = True
            else:
                shed = False
                self._q.append(r)
                depth = len(self._q)
                self._cond.notify()
        self._depth.set(depth, model=self.name)
        if shed:
            self._shed(r, "backpressure")
            raise ShedError("backpressure",
                            f"{self.name}: queue full ({depth} waiting)")
        r.event.wait()
        if r.error is not None:
            raise r.error
        return r.result

    def _shed(self, r: _SearchReq, reason: str):
        obs.observe_shed(self.route, reason=reason)
        if reason not in self._shed_seen:
            self._shed_seen.add(reason)
            obs.event("search_shed", index=self.name, reason=reason,
                      rows=int(r.rows))

    # -- dispatcher side ---------------------------------------------------

    def _worker_loop(self):
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if self._stop and not self._q:
                    return
                first = self._q.pop(0)
                depth = len(self._q)
            self._depth.set(depth, model=self.name)
            batch = self._assemble(first)
            if batch:
                self._dispatch(batch)

    def _pop_compatible(self, key) -> Optional[_SearchReq]:
        """Pop the oldest queued request sharing ``key`` (tier/k/nprobe);
        incompatible requests keep their queue position for the next
        batch seed."""
        with self._cond:
            for i, r in enumerate(self._q):
                if r.key == key:
                    return self._q.pop(i)
        return None

    def _assemble(self, first: _SearchReq) -> List[_SearchReq]:
        cfg = self.config
        lkey = f"{self.name}:{first.tier}"
        batch: List[_SearchReq] = []
        rows = 0
        tightest = float("inf")
        opened = time.perf_counter()
        candidate: Optional[_SearchReq] = first
        while True:
            now = time.perf_counter()
            if candidate is not None:
                merged = min(tightest, candidate.deadline)
                if now + cfg.margin_s > candidate.deadline:
                    self._shed(candidate, "deadline")
                    candidate.error = ShedError(
                        "deadline", f"{self.name}: deadline expired in queue")
                    candidate.event.set()
                elif (not batch
                      or (rows + candidate.rows <= cfg.max_batch
                          and self.admission.admit_more(
                              lkey, rows, candidate.rows, merged, now))):
                    batch.append(candidate)
                    rows += candidate.rows
                    tightest = merged
                else:
                    with self._cond:
                        self._q.insert(0, candidate)
                    break
                candidate = None
                continue
            if rows >= cfg.max_batch:
                break
            candidate = self._pop_compatible(first.key)
            if candidate is not None:
                continue
            if self._stop or now - opened >= cfg.max_wait_s:
                break
            if batch and not self.admission.can_wait(
                    lkey, rows, tightest, now):
                break
            time.sleep(cfg.wait_quantum_s)
        return batch

    def _dispatch(self, batch: List[_SearchReq]):
        total = sum(r.rows for r in batch)
        bucket = (bucketing.bucket_size(total)
                  if bucketing.bucketing_enabled() else total)
        lkey = f"{self.name}:{batch[0].tier}"
        try:
            with obs.span("search.dispatch", index=self.name,
                          tier=batch[0].tier, rows=int(total),
                          **_trace_attrs(batch)):
                qs = (batch[0].q if len(batch) == 1
                      else np.concatenate([r.q for r in batch], axis=0))
                t0 = time.perf_counter()
                # dispatch at the shared kb so every member's slice equals
                # its solo response bit-for-bit (row-independent kernels,
                # stable column prefix of one top-kb result)
                ids, dists = self.index.search(
                    qs, k=batch[0].kb, nprobe=batch[0].nprobe or None,
                    tier=batch[0].tier)
                dt = time.perf_counter() - t0
            self.latency.observe(lkey, bucket, dt)
            self._batches.inc(model=self.name)
            self._batch_rows.observe(total, model=self.name)
            done = time.perf_counter()
            ofs = 0
            for r in batch:
                r.result = (ids[ofs:ofs + r.rows, :r.k],
                            dists[ofs:ofs + r.rows, :r.k], r.tier)
                ofs += r.rows
                r.event.set()
                obs.observe_request(self.route, done - r.arrival,
                                    status="ok")
        except Exception as e:
            done = time.perf_counter()
            for r in batch:
                r.error = e
                r.event.set()
                obs.observe_request(self.route, done - r.arrival,
                                    status="error", error=True)

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._cond:
            depth = len(self._q)
        out = {
            "model": self.name,
            "queue_depth": depth,
            "queue_limit": self.config.queue_limit,
            "max_batch": self.config.max_batch,
            "batches": int(self._batches.value(model=self.name)),
            "workers": len(self._threads),
        }
        out.update(self.index.stats)
        return out

    def shutdown(self, timeout_s: float = 5.0):
        with self._cond:
            self._stop = True
            stranded = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in stranded:
            r.error = ShedError("shutdown", f"{self.name}: worker shut down")
            r.event.set()
        for t in self._threads:
            t.join(timeout=timeout_s)


# ---------------------------------------------------------------------------
# Token-level continuous batching: the generative decode engine
# ---------------------------------------------------------------------------


class _Stream:
    """One in-flight generation request: host-side bookkeeping only."""

    __slots__ = ("prompt", "max_new", "eos", "deadline", "arrival", "out",
                 "state", "fed", "cached", "generated", "next_tok", "pages",
                 "slot", "last_emit", "sid")

    def __init__(self, prompt: List[int], max_new: int, eos: Optional[int],
                 deadline: float, arrival: float, sid: int):
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.deadline = deadline
        self.arrival = arrival
        self.sid = sid
        self.out: "queue.Queue" = queue.Queue()
        self.state = "queued"        # queued -> prefill -> decode -> done
        self.fed = 0                 # prompt tokens already dispatched
        self.cached = 0              # tokens whose k/v live in the cache
        self.generated = 0
        self.next_tok: Optional[int] = None   # emitted but not yet cached
        self.pages: List[int] = []   # owned page ids (paged mode)
        self.slot: Optional[int] = None       # owned strip (contiguous mode)
        self.last_emit: Optional[float] = None


class GenerateStream:
    """Consumer handle for one generation request: iterate to receive token
    ids as the engine emits them (token-level streaming — each item was a
    separate decode step server-side). After iteration ends,
    ``finish_reason`` is one of ``eos`` / ``length`` / ``shed:deadline`` /
    ``shutdown`` and ``ttft_s`` holds the measured time to first token."""

    def __init__(self, stream: _Stream):
        self._s = stream
        self.finish_reason: Optional[str] = None
        self.ttft_s: Optional[float] = None
        self.tokens: List[int] = []

    def __iter__(self):
        while True:
            kind, payload = self._s.out.get()
            if kind == "token":
                if not self.tokens:
                    self.ttft_s = time.perf_counter() - self._s.arrival
                self.tokens.append(payload)
                yield payload
            elif kind == "done":
                self.finish_reason = payload
                return
            else:  # "error"
                self.finish_reason = "error"
                raise payload


class GenerateWorker:
    """Token-level continuous batching for ONE generative model.

    The unit of scheduling is a single decode STEP, not a request: every
    engine iteration (one thread, one device dispatch at a time)

    1. **admits** queued streams into free cache slots — join happens at a
       token boundary, mid-flight streams never restart;
    2. runs at most ONE prefill chunk for the oldest still-prefilling
       stream (``prefill_chunk`` tokens of ITS prompt) — the prefill/decode
       split: a long prompt costs in-flight streams one chunk of latency
       per iteration, never its whole length;
    3. runs ONE decode step over ALL streams in decode state — each one
       advances one token, finished streams leave at that boundary and
       their pages return to the free list immediately.

    Prompts prefill at batch 1 and decode batches pad up the bucket
    ladder, so every dispatch lands on the AOT-warm ``decode.step``
    executable set (zero request-path compiles) and batched greedy output
    is bit-exact vs serving each stream alone: batch padding contributes
    exact-zero attention weight (ops/flash_attention.decode_attention) and
    rows are independent.

    Deadlines are repriced per remaining token budget
    (:class:`~.admission.TokenAdmission`): shed-on-arrival prices
    prefill + ``max_new`` × measured ITL; every emitted token reprices the
    REMAINder, so a stream that can no longer finish in time stops
    stealing batch slots mid-flight (``finish_reason == "shed:deadline"``).
    """

    def __init__(self, name: str, model, program,
                 config: Optional[GenerateConfig] = None,
                 latency: Optional[LatencyModel] = None,
                 ladder: Optional[bucketing.BucketLadder] = None):
        self.name = name
        self.model = model
        self.program = program
        self.config = config or GenerateConfig.from_env()
        self.route = f"generate.{name}"
        self.latency = latency or LatencyModel(
            min_samples=self.config.min_samples)
        self.admission = TokenAdmission(self.latency, self.config,
                                        ladder=ladder)
        self.ladder = ladder or bucketing.ladder_from_env()
        self._pg = program.page_tokens
        self._cond = threading.Condition()
        self._q: List[_Stream] = []
        self._active: List[_Stream] = []
        self._stop = False
        self._sid = 0
        self._shed_seen: set = set()
        if program.paged:
            # page 0 is the program's scratch page — never hand it out
            self._free_pages = list(range(1, 1 + program.max_batch
                                          * program.max_pages))
            self._free_slots = None
        else:
            self._free_pages = None
            self._free_slots = list(range(program.max_batch))
        self.stats_counters = {"joins": 0, "leaves": 0, "generated": 0,
                               "shed_midstream": 0, "max_occupancy": 0}
        self._thread = threading.Thread(target=self._engine_loop, daemon=True,
                                        name=f"generate-{name}")
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(self, prompt, max_new: Optional[int] = None,
               eos: Optional[int] = None,
               deadline_s: Optional[float] = None) -> GenerateStream:
        """Enqueue one generation request; returns a :class:`GenerateStream`
        immediately (tokens arrive as the engine emits them). Raises
        :class:`ShedError` on arrival-time shedding, ``ValueError`` on a
        request the cache can never hold."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("generate: prompt must carry at least one token")
        if max_new is None:
            max_new = self.config.max_new_default
        max_new = int(max_new)
        if max_new < 1:
            raise ValueError("generate: max_tokens must be >= 1")
        if len(prompt) + max_new > self.program.capacity:
            raise ValueError(
                f"generate: prompt ({len(prompt)}) + max_tokens ({max_new}) "
                f"exceeds model capacity {self.program.capacity}")
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        with self._cond:
            self._sid += 1
            sid = self._sid
        s = _Stream(prompt, max_new, eos, now + deadline_s, now, sid)
        # arrival repricing: prefill cost + max_new tokens at measured ITL
        if self.admission.infeasible(self.name, len(prompt), max_new,
                                     s.deadline, now):
            self._shed(s, "deadline")
            raise ShedError("deadline",
                            f"{self.name}: token budget ({max_new}) at "
                            f"measured ITL cannot meet deadline "
                            f"{deadline_s * 1e3:.1f}ms")
        with self._cond:
            if self._stop:
                raise ShedError("shutdown", f"{self.name}: worker shut down")
            if len(self._q) >= self.config.queue_limit:
                shed = True
            else:
                shed = False
                self._q.append(s)
                self._cond.notify()
        if shed:
            self._shed(s, "backpressure")
            raise ShedError("backpressure",
                            f"{self.name}: generate queue full")
        return GenerateStream(s)

    def _shed(self, s: _Stream, reason: str):
        obs.observe_shed(self.route, reason=reason)
        if reason not in self._shed_seen:
            self._shed_seen.add(reason)
            obs.event("generate_shed", model=self.name, reason=reason)

    # -- engine ------------------------------------------------------------

    def _pages_needed(self, s: _Stream) -> int:
        return max(1, math.ceil((len(s.prompt) + s.max_new) / self._pg))

    def _admit(self, now: float):
        """Move queued streams into free cache slots (token-boundary join).
        Expired or no-longer-feasible queued streams shed here — before
        they cost a single dispatch."""
        while True:
            with self._cond:
                if not self._q or len(self._active) \
                        >= self.config.decode_batch_max:
                    return
                need = self._pages_needed(self._q[0])
                if self.program.paged:
                    if len(self._free_pages) < need:
                        return
                elif not self._free_slots:
                    return
                s = self._q.pop(0)
            if now + self.config.margin_s > s.deadline:
                self._shed(s, "deadline")
                s.out.put(("done", "shed:deadline"))
                continue
            with self._cond:
                if self.program.paged:
                    n = self._pages_needed(s)
                    s.pages = [self._free_pages.pop()
                               for _ in range(n)]
                else:
                    s.slot = self._free_slots.pop()
                s.state = "prefill"
                self._active.append(s)
            self.stats_counters["joins"] += 1
            occ = len(self._active)
            if occ > self.stats_counters["max_occupancy"]:
                self.stats_counters["max_occupancy"] = occ
            obs.set_decode_occupancy(self.name, occ)

    def _leave(self, s: _Stream, reason: str):
        """Stream leaves the batch at a token boundary; its cache capacity
        is reusable by the NEXT admit immediately."""
        with self._cond:
            if s in self._active:
                self._active.remove(s)
            if self.program.paged:
                self._free_pages.extend(s.pages)
                s.pages = []
            elif s.slot is not None:
                self._free_slots.append(s.slot)
                s.slot = None
        s.state = "done"
        self.stats_counters["leaves"] += 1
        obs.set_decode_occupancy(self.name, len(self._active))
        s.out.put(("done", reason))
        status = "ok" if reason in ("eos", "length") else "shed"
        obs.observe_request(self.route, time.perf_counter() - s.arrival,
                            status=status)

    def _emit(self, s: _Stream, tok: int, step_bucket: int, now: float):
        """Deliver one token; record TTFT/ITL; decide finish/shed/continue."""
        s.generated += 1
        self.stats_counters["generated"] += 1
        if s.last_emit is None:
            obs.observe_ttft(self.route, now - s.arrival)
        else:
            obs.observe_itl(self.route, now - s.last_emit)
        s.last_emit = now
        s.out.put(("token", tok))
        if s.eos is not None and tok == s.eos:
            self._leave(s, "eos")
        elif s.generated >= s.max_new:
            self._leave(s, "length")
        elif self.admission.should_shed(self.name, s.max_new - s.generated,
                                        s.deadline, now,
                                        batch_rows=step_bucket):
            self.stats_counters["shed_midstream"] += 1
            self._shed(s, "deadline")
            self._leave(s, "shed:deadline")
        else:
            s.state = "decode"
            s.next_tok = tok

    def _table_for(self, streams: List[_Stream], np_bucket: int):
        if self.program.paged:
            table = np.zeros((len(streams), np_bucket), np.int32)
            for i, s in enumerate(streams):
                # only pages the step can touch fit the window; the rest of
                # the allocation enters the table as later positions need it
                n = min(len(s.pages), np_bucket)
                table[i, :n] = s.pages[:n]
            return table
        return np.asarray(
            [s.slot if s.slot is not None else self.program.max_batch
             for s in streams], np.int32)

    def _np_bucket(self, max_pos: int) -> int:
        if not self.program.paged:
            return 0
        used = max(1, math.ceil(max_pos / self._pg))
        return min(self.ladder.bucket(used), self.ladder.bucket(
            self.program.max_pages))

    def _prefill_one(self):
        """One chunk of the OLDEST prefilling stream (batch 1 — the same
        dispatch shape an unbatched client would produce)."""
        s = next((t for t in self._active if t.state == "prefill"), None)
        if s is None:
            return False
        chunk = s.prompt[s.fed:s.fed + self.config.prefill_chunk]
        tc = self.ladder.bucket(len(chunk)) if len(chunk) > 1 else 1
        npb = self._np_bucket(s.fed + len(chunk))
        tokens = np.zeros((1, tc), np.int32)
        tokens[0, :len(chunk)] = chunk
        bucketing.telemetry().record_hit("serve.gen.prefill", len(chunk), tc)
        t0 = time.perf_counter()
        _, ids = self.program.dispatch(
            self._table_for([s], npb), [s.cached], tokens, [len(chunk)])
        tok = int(ids[0])  # host sync: the emitted token IS the product
        dt = time.perf_counter() - t0
        self.latency.observe(f"{self.name}:prefill", tc, dt)
        s.fed += len(chunk)
        s.cached += len(chunk)
        if s.fed >= len(s.prompt):
            # the final prefill chunk's logits ARE the first token
            self._emit(s, tok, 1, time.perf_counter())
        return True

    def _decode_step(self):
        """ONE token step over every decode-state stream, padded up the
        batch bucket ladder."""
        streams = [s for s in self._active if s.state == "decode"]
        if not streams:
            return False
        streams.sort(key=lambda s: s.sid)  # deterministic row order
        B = len(streams)
        bb = (self.ladder.bucket(B) if bucketing.bucketing_enabled() else B)
        bb = min(bb, self.ladder.bucket(self.config.decode_batch_max))
        npb = self._np_bucket(max(s.cached + 1 for s in streams))
        table = self._table_for(streams, npb)
        if self.program.paged and bb > B:
            table = np.concatenate(
                [table, np.zeros((bb - B, npb), np.int32)], axis=0)
        elif not self.program.paged and bb > B:
            table = np.concatenate(
                [table, np.full((bb - B,), self.program.max_batch,
                                np.int32)], axis=0)
        lengths = np.zeros((bb,), np.int32)
        tokens = np.zeros((bb, 1), np.int32)
        n_new = np.zeros((bb,), np.int32)
        for i, s in enumerate(streams):
            lengths[i] = s.cached
            tokens[i, 0] = s.next_tok
            n_new[i] = 1
        bucketing.telemetry().record_hit("serve.gen.decode", B, bb)
        t0 = time.perf_counter()
        _, ids = self.program.dispatch(table, lengths, tokens, n_new)
        ids = np.asarray(ids)  # host sync: tokens fan out to streams now
        dt = time.perf_counter() - t0
        self.latency.observe(f"{self.name}:decode", bb, dt)
        now = time.perf_counter()
        for i, s in enumerate(streams):
            s.cached += 1
            self._emit(s, int(ids[i]), bb, now)
        return True

    def _engine_loop(self):
        while True:
            with self._cond:
                while not self._q and not self._active and not self._stop:
                    self._cond.wait()
                if self._stop:
                    return
            self._admit(time.perf_counter())
            try:
                did = self._prefill_one()
                did = self._decode_step() or did
            except Exception as e:  # fail every in-flight stream, keep serving
                with self._cond:
                    failing = list(self._active)
                for s in failing:
                    # error event first: the consumer stops at the first
                    # terminal event, _leave's "done" is just queue residue
                    s.out.put(("error", e))
                    self._leave(s, "shutdown")
                continue
            if not did:
                # active streams exist but none dispatchable (all queued
                # behind admit) — yield briefly rather than spin
                time.sleep(0.0002)

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._cond:
            depth = len(self._q)
            occ = len(self._active)
        out = dict(self.stats_counters)
        out.update({"model": self.name, "queue_depth": depth,
                    "occupancy": occ,
                    "decode_batch_max": self.config.decode_batch_max,
                    "kv_page_tokens": self.config.kv_page_tokens,
                    "paged": self.program.paged,
                    "capacity": self.program.capacity})
        return out

    def shutdown(self, timeout_s: float = 5.0):
        with self._cond:
            self._stop = True
            stranded = list(self._q) + list(self._active)
            self._q.clear()
            self._active.clear()
            self._cond.notify_all()
        for s in stranded:
            s.out.put(("done", "shutdown"))
        self._thread.join(timeout=timeout_s)
