"""Continuous-batching scheduler: per-model queues, coalescing dispatchers.

One :class:`ModelWorker` per served model owns a bounded request queue and
a small pool of dispatcher threads. Each dispatcher runs the continuous-
batching loop:

1. **Pop** the oldest request (the batch seed).
2. **Admit** — coalesce compatible queued requests into the forming batch
   while the grown batch's bucket still meets the tightest admitted
   deadline with margin (:class:`~.admission.AdmissionController`), waiting
   in sub-millisecond quanta for more traffic only while that same check
   says the wait is affordable (admit-until-deadline-margin, not a fixed
   drain tick).
3. **Dispatch** OUTSIDE the admission lock: concatenate rows, let
   ``model.output`` pad up the shared bucket ladder (one executable per
   bucket; AOT-warmed at registration so the request path never compiles),
   slice results back per request, measure the execution latency into the
   :class:`~.admission.LatencyModel`.

Overload protection is fail-fast, never queue-unboundedly:

- **Backpressure** — a full queue sheds at submit (→ HTTP 429).
- **Deadline shedding** — a request whose measured bucket latency cannot
  meet its deadline is shed at arrival, and one that expires while queued
  is shed at assembly instead of wasting a dispatch (→ HTTP 503).

Both paths record ``dl4j_requests_total{status="shed"}`` +
``dl4j_shed_total{reason}`` and burn SLO error budget (obs/slo.py), so the
burn-rate gauge reacts to overload exactly as it does to latency misses.

Lock discipline (enforced by graftlint's lock-discipline rule): everything
under ``self._cond`` is host-side queue/float arithmetic — the device
dispatch, the result materialization, and the per-request fan-out all
happen with the lock released, so producers are never stalled behind XLA.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.serve.admission import (
    AdmissionController, LatencyModel, ServeConfig)
from deeplearning4j_tpu.utils import bucketing

__all__ = ["ModelWorker", "ShedError", "ServeConfig"]


class ShedError(RuntimeError):
    """A request the serving tier refused to run. ``reason`` is
    ``backpressure`` (queue full → HTTP 429), ``deadline`` (cannot meet the
    request's deadline → HTTP 503) or ``shutdown`` (→ HTTP 503)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason

    @property
    def http_status(self) -> int:
        return 429 if self.reason == "backpressure" else 503


class _Req:
    __slots__ = ("x", "rows", "deadline", "arrival", "event", "result",
                 "error")

    def __init__(self, x, deadline: float, arrival: float):
        self.x = x
        self.rows = len(x)
        self.deadline = deadline
        self.arrival = arrival
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


class ModelWorker:
    """Deadline-aware continuous-batching front for ONE model.

    ``submit`` blocks the calling thread until its rows come back (or
    raises :class:`ShedError`); the dispatcher pool coalesces concurrent
    callers into bucket-ladder batches. ``latency`` may be shared across
    workers (the registry shares one :class:`LatencyModel` so /metrics has
    a single family) — estimates are keyed per model name.
    """

    def __init__(self, name: str, model, config: Optional[ServeConfig] = None,
                 latency: Optional[LatencyModel] = None,
                 ladder: Optional[bucketing.BucketLadder] = None):
        self.name = name
        self.model = model
        self.config = config or ServeConfig.from_env()
        self.route = f"serve.{name}"
        self.latency = latency or LatencyModel(
            min_samples=self.config.min_samples)
        self.admission = AdmissionController(self.latency, self.config,
                                             ladder=ladder)
        self._cond = threading.Condition()
        self._q: List[_Req] = []
        self._stop = False
        self._shed_seen: set = set()
        self._batches = obs.counter(
            "dl4j_serve_batches_total",
            "coalesced dispatches by model", ("model",))
        self._batch_rows = obs.histogram(
            "dl4j_serve_batch_rows",
            "real rows per coalesced dispatch (fill, before bucket padding)",
            ("model",))
        self._depth = obs.gauge(
            "dl4j_serve_queue_depth",
            "requests waiting in the per-model serving queue", ("model",))
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"serve-{name}-{i}")
            for i in range(max(1, self.config.workers))]
        for t in self._threads:
            t.start()

    # -- producer side -----------------------------------------------------

    def submit(self, x, deadline_s: Optional[float] = None) -> np.ndarray:
        """Serve one request of ``len(x)`` rows. ``deadline_s`` is relative
        to now (defaults to ``ServeConfig.default_deadline_s``); the call
        blocks until the rows are served, or raises :class:`ShedError` /
        the model's own failure."""
        x = np.asarray(x)
        if x.ndim < 1 or len(x) == 0:
            raise ValueError("request must carry at least one row")
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        r = _Req(x, now + deadline_s, now)
        # arrival feasibility BEFORE touching the queue: a request whose
        # bucket measurably overruns its own deadline wastes queue space
        # and device time — reject it while it is cheapest (503 semantics)
        if self.admission.infeasible(self.name, r.rows, r.deadline, now):
            self._shed(r, "deadline")
            raise ShedError("deadline",
                            f"{self.name}: measured bucket latency cannot "
                            f"meet deadline {deadline_s * 1e3:.1f}ms")
        with self._cond:
            if self._stop:
                raise ShedError("shutdown", f"{self.name}: worker shut down")
            if len(self._q) >= self.config.queue_limit:
                depth = len(self._q)
                shed = True
            else:
                shed = False
                self._q.append(r)
                depth = len(self._q)
                self._cond.notify()
        self._depth.set(depth, model=self.name)
        if shed:
            self._shed(r, "backpressure")
            raise ShedError("backpressure",
                            f"{self.name}: queue full ({depth} waiting)")
        r.event.wait()
        if r.error is not None:
            raise r.error
        return r.result

    # -- shed accounting ---------------------------------------------------

    def _shed(self, r: _Req, reason: str):
        obs.observe_shed(self.route, reason=reason)
        if reason not in self._shed_seen:  # first occurrence: one event
            self._shed_seen.add(reason)
            obs.event("serve_shed", model=self.name, reason=reason,
                      rows=int(r.rows))

    # -- dispatcher side ---------------------------------------------------

    def _worker_loop(self):
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if self._stop and not self._q:
                    return
                first = self._q.pop(0)
                depth = len(self._q)
            self._depth.set(depth, model=self.name)
            batch = self._assemble(first)
            if batch:
                self._dispatch(batch)

    def _assemble(self, first: _Req) -> List[_Req]:
        """The admission loop: grow [first] while the admission controller
        approves, shedding queued requests that expired. Returns the batch
        to dispatch (possibly empty if every candidate expired)."""
        cfg = self.config
        batch: List[_Req] = []
        rows = 0
        tightest = float("inf")
        opened = time.perf_counter()
        candidate: Optional[_Req] = first
        while True:
            now = time.perf_counter()
            if candidate is not None:
                merged = min(tightest, candidate.deadline)
                if now + cfg.margin_s > candidate.deadline:
                    # expired while queued: a late response is a failed
                    # response that also ate device time — shed instead
                    self._shed(candidate, "deadline")
                    candidate.error = ShedError(
                        "deadline", f"{self.name}: deadline expired in queue")
                    candidate.event.set()
                elif not batch or self.admission.admit_more(
                        self.name, rows, candidate.rows, merged, now):
                    batch.append(candidate)
                    rows += candidate.rows
                    tightest = merged
                else:
                    # would overrun the tightest admitted deadline (or the
                    # batch cap): leave it at the queue head for the next
                    # batch — this batch dispatches on the last bucket that
                    # stays feasible
                    with self._cond:
                        self._q.insert(0, candidate)
                    break
                candidate = None
                continue
            if rows >= cfg.max_batch:
                break
            with self._cond:
                if self._q:
                    candidate = self._q.pop(0)
                    continue
            if self._stop or now - opened >= cfg.max_wait_s:
                break
            if batch and not self.admission.can_wait(
                    self.name, rows, tightest, now):
                break
            time.sleep(cfg.wait_quantum_s)
        return batch

    def _dispatch(self, batch: List[_Req]):
        total = sum(r.rows for r in batch)
        bucket = (bucketing.bucket_size(total)
                  if bucketing.bucketing_enabled() else total)
        bucketing.telemetry().record_hit(self.route, total, bucket)
        try:
            xs = (batch[0].x if len(batch) == 1
                  else np.concatenate([r.x for r in batch], axis=0))
            t0 = time.perf_counter()
            # model.output pads up the shared ladder itself, so this
            # dispatch hits the SAME executable (and AOT warm entry) a
            # direct caller would — the basis of coalescing bit-exactness
            out = np.asarray(self.model.output(xs))
            dt = time.perf_counter() - t0
            self.latency.observe(self.name, bucket, dt)
            self._batches.inc(model=self.name)
            self._batch_rows.observe(total, model=self.name)
            done = time.perf_counter()
            ofs = 0
            for r in batch:
                r.result = out[ofs:ofs + r.rows]
                ofs += r.rows
                r.event.set()
                obs.observe_request(self.route, done - r.arrival,
                                    status="ok")
        except Exception as e:  # propagate to every waiter, keep serving
            done = time.perf_counter()
            for r in batch:
                r.error = e
                r.event.set()
                obs.observe_request(self.route, done - r.arrival,
                                    status="error", error=True)

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._cond:
            depth = len(self._q)
        return {
            "model": self.name,
            "queue_depth": depth,
            "queue_limit": self.config.queue_limit,
            "max_batch": self.config.max_batch,
            "batches": int(self._batches.value(model=self.name)),
            "workers": len(self._threads),
        }

    def shutdown(self, timeout_s: float = 5.0):
        with self._cond:
            self._stop = True
            stranded = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in stranded:
            r.error = ShedError("shutdown", f"{self.name}: worker shut down")
            r.event.set()
        for t in self._threads:
            t.join(timeout=timeout_s)
