"""Shared HTTP plumbing for the repo's two stdlib servers.

``ui/server.py`` (dashboard) and ``serve/server.py`` (inference) carry the
same non-negotiables on every route: the serving-SLO envelope (per-route
latency histogram + ``dl4j_requests_total{route,status}`` + burn rate via
obs/slo.py), the ``dl4j_http_in_flight`` gauge, quiet request logging, and
the operational endpoints ``/metrics`` (Prometheus text exposition) and
``/healthz``. This module owns those once:

- :class:`InFlight` — the shared in-flight counter → gauge;
- :class:`ObservedHandler` — a BaseHTTPRequestHandler that wraps
  ``handle_get``/``handle_post`` (return the status they sent) in the SLO
  envelope and answers ``/metrics`` + ``/healthz`` before delegating;
- :func:`start_server` — ThreadingHTTPServer on 127.0.0.1 + daemon thread.

Subclasses override ``handle_get``/``handle_post`` and reply through
``send_body``/``send_json``/``send_error_body`` so Content-Length is always
right.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import urlparse

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.obs import fleet

__all__ = ["InFlight", "ObservedHandler", "start_server"]

PROM_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


class InFlight:
    """Requests currently inside a handler, mirrored to the
    ``dl4j_http_in_flight`` gauge (shared by every server in the process —
    the gauge is process-wide saturation, not per-listener)."""

    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def note(self, delta: int) -> None:
        with self._lock:
            self._n += delta
            v = self._n
        if obs.enabled():
            obs.gauge("dl4j_http_in_flight",
                      "HTTP requests currently being served").set(v)


class ObservedHandler(BaseHTTPRequestHandler):
    """SLO-observed request handler with the common operational routes.

    Class attribute ``inflight`` (an :class:`InFlight`) is injected by the
    server that mounts the handler. ``slo_route(path)`` may be overridden
    to collapse high-cardinality paths (e.g. per-model predict URLs) into a
    bounded route label set.
    """

    inflight: Optional[InFlight] = None
    # the request's trace context (obs/fleet.py): adopted from an inbound
    # W3C ``traceparent`` header (same trace, fresh span id) or minted at
    # this front door; echoed on every reply and stamped onto every
    # span/event recorded while the handler runs
    trace: Optional[fleet.TraceContext] = None

    def log_message(self, *a):  # quiet: obs carries the signal
        pass

    # -- envelope ----------------------------------------------------------

    def slo_route(self, path: str) -> str:
        return path

    def _observed(self, handler):
        route = self.slo_route(urlparse(self.path).path)
        inbound = fleet.TraceContext.parse(self.headers.get("traceparent"))
        self.trace = inbound.child() if inbound else fleet.TraceContext.mint()
        if self.inflight is not None:
            self.inflight.note(1)
        t0 = time.perf_counter()
        status = 500
        try:
            with fleet.trace_scope(self.trace), \
                    obs.span("http.request", route=route,
                             method=self.command):
                status = handler()
        finally:
            if self.inflight is not None:
                self.inflight.note(-1)
            obs.observe_request(route, time.perf_counter() - t0,
                                status=str(status), error=status >= 500)

    def do_GET(self):
        self._observed(self._get_with_common)

    def do_POST(self):
        self._observed(self.handle_post)

    def _get_with_common(self) -> int:
        route = urlparse(self.path).path
        if route == "/metrics":
            return self.send_body(200, obs.prometheus_text().encode(),
                                  PROM_CTYPE)
        if route == "/healthz":
            return self.send_json(200, {"status": "ok"})
        return self.handle_get()

    # -- overridables ------------------------------------------------------

    def handle_get(self) -> int:
        self.send_response(404)
        self.end_headers()
        return 404

    def handle_post(self) -> int:
        self.send_response(404)
        self.end_headers()
        return 404

    # -- reply helpers -----------------------------------------------------

    def send_body(self, status: int, body: bytes, ctype: str,
                  headers: Tuple[Tuple[str, str], ...] = ()) -> int:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if self.trace is not None:
            self.send_header("traceparent", self.trace.header())
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        return status

    def send_json(self, status: int, payload,
                  headers: Tuple[Tuple[str, str], ...] = ()) -> int:
        return self.send_body(status, json.dumps(payload).encode(),
                              "application/json", headers)

    def read_json(self):
        n = int(self.headers.get("Content-Length", "0"))
        return json.loads(self.rfile.read(n).decode("utf-8"))


def start_server(handler_cls, port: int = 0,
                 host: str = "127.0.0.1") -> Tuple[ThreadingHTTPServer,
                                                   threading.Thread, int]:
    """Bind ``handler_cls`` and serve it from a daemon thread. Returns
    ``(httpd, thread, bound_port)`` (``port=0`` lets the OS pick)."""
    httpd = ThreadingHTTPServer((host, port), handler_cls)
    bound = httpd.server_address[1]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread, bound
