"""Model registry: the import → AOT-warm → serve pipeline, per model pool.

``ModelRegistry`` owns every :class:`~.scheduler.ModelWorker` in the
process. Models enter one of two ways:

- ``register(name, model)`` — an already-constructed model object;
- ``load(name, path)``      — a path, format-detected by
  :func:`deeplearning4j_tpu.modelimport.import_model` (Keras ``.h5`` or
  DL4J ``.zip``).

Either way the model runs the same warm pipeline before it takes traffic:

1. **restore** — if an ``.aotbundle`` sidecar exists (``bundle`` argument,
   or ``<path>.aotbundle`` next to a loaded file) and persistence is
   validated for this backend (``nn/aot.py``), its serialized executables
   are installed so even the first warm call skips XLA entirely;
2. **warm** — ``nn.aot.warm_serving`` AOT-compiles the inference path for
   every ladder bucket reachable by coalesced batches up to the worker's
   ``max_batch``, so the REQUEST PATH NEVER COMPILES (the zero-compile
   gate in tools/serve_smoke.sh);
3. **persist** — the now-warm executables are saved back to the bundle
   path (best-effort, validation-gated) so the next process restores
   instead of recompiling.

All latency measurements share one :class:`~.admission.LatencyModel`
(single ``dl4j_serve_exec_seconds`` family on /metrics), keyed per model.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.serve.admission import (
    GenerateConfig, LatencyModel, ServeConfig)
from deeplearning4j_tpu.serve.scheduler import (
    GenerateWorker, ModelWorker, SearchWorker)

__all__ = ["ModelRegistry"]


class ModelRegistry:
    def __init__(self, config: Optional[ServeConfig] = None):
        self.config = config or ServeConfig.from_env()
        self.latency = LatencyModel(min_samples=self.config.min_samples)
        self._lock = threading.Lock()
        self._workers: Dict[str, ModelWorker] = {}
        self._generators: Dict[str, GenerateWorker] = {}
        self._searchers: Dict[str, SearchWorker] = {}
        self._meta: Dict[str, Dict[str, object]] = {}

    # -- intake ------------------------------------------------------------

    def register(self, name: str, model, warm: bool = True,
                 bundle: Optional[str] = None) -> ModelWorker:
        """Put ``model`` behind a continuous-batching worker under ``name``.
        Replaces (and shuts down) any worker already bound to the name."""
        meta = self._warm_pipeline(name, model, warm=warm, bundle=bundle)
        worker = ModelWorker(name, model, config=self.config,
                             latency=self.latency)
        with self._lock:
            old = self._workers.pop(name, None)
            self._workers[name] = worker
            self._meta[name] = meta
        if old is not None:
            old.shutdown()
        obs.event("serve_model_loaded", model=name, **{
            k: meta[k] for k in ("source", "model_class", "warmed", "restored",
                                 "warm_seconds")})
        return worker

    def register_generate(self, name: str, model, warm: bool = True,
                          bundle: Optional[str] = None,
                          config: Optional[GenerateConfig] = None,
                          capacity: Optional[int] = None) -> GenerateWorker:
        """Put an autoregressive LM behind a token-level continuous-batching
        decode engine under ``name`` (``/v1/generate``).

        Same lifecycle as :meth:`register` but for the DECODE executable
        set: tuner selections land first (``kv_page_tokens`` /
        ``decode_batch_max`` are scope=serve knobs, so ``GenerateConfig``
        is read AFTER ``tune.maybe_apply``), then the
        :class:`~deeplearning4j_tpu.nn.decode.DecodeProgram`'s jitted step
        registers on the model's AOT site table — a ``bundle`` restore
        installs its serialized executables BEFORE ``warm`` enumerates the
        (batch x chunk x table) bucket grid, and the now-warm set persists
        back to the bundle, so a cold process streams tokens with zero
        request-path compiles."""
        import os as _os

        from deeplearning4j_tpu.nn import aot
        from deeplearning4j_tpu.nn.decode import DecodeProgram

        if getattr(model, "params", None) is None:
            model.init()
        if _os.environ.get("DL4J_TPU_TUNE"):
            from deeplearning4j_tpu import tune as _tune

            _tune.maybe_apply(model, "serve")
        cfg = config or GenerateConfig.from_env()
        program = DecodeProgram(
            model, page_tokens=cfg.kv_page_tokens,
            max_batch=cfg.decode_batch_max, prefill_chunk=cfg.prefill_chunk,
            paged=cfg.paged, capacity=capacity)
        restored = 0
        if bundle:
            restored = aot.restore_bundle(model, bundle)
        warmed = 0
        warm_dt = 0.0
        if warm:
            t0 = time.perf_counter()
            warmed = program.warm()
            warm_dt = time.perf_counter() - t0
            if bundle:
                aot.save_bundle(model, bundle)
        worker = GenerateWorker(name, model, program, config=cfg,
                                latency=self.latency)
        meta = {
            "source": "object",
            "model_class": type(model).__name__,
            "warmed": int(warmed),
            "restored": int(restored),
            "warm_seconds": round(warm_dt, 4),
            "bundle": bundle,
            "generate": True,
        }
        with self._lock:
            old = self._generators.pop(name, None)
            self._generators[name] = worker
            self._meta[f"generate:{name}"] = meta
        if old is not None:
            old.shutdown()
        obs.event("serve_model_loaded", model=name, mode="generate", **{
            k: meta[k] for k in ("source", "model_class", "warmed",
                                 "restored", "warm_seconds")})
        return worker

    def register_index(self, name: str, index, warm: bool = True,
                       bundle: Optional[str] = None) -> SearchWorker:
        """Put a :class:`~deeplearning4j_tpu.search.index.VectorIndex`
        behind a signature-coalescing worker under ``name``
        (``/v1/search``).

        Same lifecycle as :meth:`register`: an ``.aotbundle`` sidecar (if
        given) restores serialized search executables BEFORE the warm pass
        enumerates the (B, k, nprobe) signature grid — on a cold
        bundle-restored process every grid entry is a cache hit and the
        request path never compiles. The tier knobs (``ivf_nlist`` /
        ``ivf_nprobe`` / ``search_batch_max``) act at index BUILD time, so
        a tuner trial rebuilds in its subprocess; by registration the index
        shape is already final."""
        from deeplearning4j_tpu.nn import aot

        restored = 0
        if bundle:
            restored = aot.restore_bundle(index, bundle)
        warmed = 0
        warm_dt = 0.0
        if warm:
            t0 = time.perf_counter()
            warmed = index.warm()
            warm_dt = time.perf_counter() - t0
            if bundle:
                aot.save_bundle(index, bundle)
        worker = SearchWorker(name, index, config=self.config,
                              latency=self.latency)
        meta = {
            "source": "object",
            "model_class": type(index).__name__,
            "warmed": int(warmed),
            "restored": int(restored),
            "warm_seconds": round(warm_dt, 4),
            "bundle": bundle,
            "search": True,
        }
        with self._lock:
            old = self._searchers.pop(name, None)
            self._searchers[name] = worker
            self._meta[f"search:{name}"] = meta
        if old is not None:
            old.shutdown()
        obs.event("serve_model_loaded", model=name, mode="search", **{
            k: meta[k] for k in ("source", "model_class", "warmed",
                                 "restored", "warm_seconds")})
        return worker

    def load(self, name: str, path: str, warm: bool = True,
             bundle: Optional[str] = None) -> ModelWorker:
        """Import the model at ``path`` (format auto-detected) and register
        it. ``bundle`` defaults to the ``<path>.aotbundle`` sidecar."""
        from deeplearning4j_tpu import modelimport
        from deeplearning4j_tpu.nn import aot

        model = modelimport.import_model(path)
        if bundle is None:
            bundle = aot.bundle_path_for(path)
        worker = self.register(name, model, warm=warm, bundle=bundle)
        with self._lock:
            self._meta[name]["source"] = str(path)
        return worker

    def _warm_pipeline(self, name: str, model, warm: bool,
                       bundle: Optional[str]) -> Dict[str, object]:
        from deeplearning4j_tpu.nn import aot

        if getattr(model, "params", None) is None:
            model.init()
        import os as _os

        if _os.environ.get("DL4J_TPU_TUNE"):
            # tuner winner must land before warm_serving compiles buckets
            from deeplearning4j_tpu import tune as _tune

            _tune.maybe_apply(model, "serve")
        restored = 0
        warmed = 0
        warm_dt = 0.0
        if warm:
            t0 = time.perf_counter()
            restored, warmed = aot.warm_serving_bundled(
                model, self.config.max_batch, bundle)
            warm_dt = time.perf_counter() - t0
        elif bundle:
            restored = aot.restore_bundle(model, bundle)
        return {
            "source": "object",
            "model_class": type(model).__name__,
            "warmed": int(warmed),
            "restored": int(restored),
            "warm_seconds": round(warm_dt, 4),
            "bundle": bundle,
        }

    # -- lookup / introspection -------------------------------------------

    def worker(self, name: str) -> Optional[ModelWorker]:
        with self._lock:
            return self._workers.get(name)

    def generator(self, name: str) -> Optional[GenerateWorker]:
        with self._lock:
            return self._generators.get(name)

    def searcher(self, name: Optional[str] = None) -> Optional[SearchWorker]:
        """Search worker by name; with ``name=None`` (or "default") and
        exactly one index registered, that index — the legacy /knn routes
        carry no index name."""
        with self._lock:
            if name in (None, "default") and name not in self._searchers:
                if len(self._searchers) == 1:
                    return next(iter(self._searchers.values()))
                return None
            return self._searchers.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(set(self._workers) | set(self._generators)
                          | set(self._searchers))

    def describe(self) -> List[Dict[str, object]]:
        """One JSON-friendly row per served model (GET /v1/models)."""
        with self._lock:
            pairs = [(self._workers[n], dict(self._meta.get(n, {})))
                     for n in sorted(self._workers)]
            pairs += [(self._generators[n],
                       dict(self._meta.get(f"generate:{n}", {})))
                      for n in sorted(self._generators)]
            pairs += [(self._searchers[n],
                       dict(self._meta.get(f"search:{n}", {})))
                      for n in sorted(self._searchers)]
        rows = []
        for worker, meta in pairs:
            row = worker.stats()
            row.update(meta)
            rows.append(row)
        return rows

    def shutdown(self):
        with self._lock:
            workers = (list(self._workers.values())
                       + list(self._generators.values())
                       + list(self._searchers.values()))
            self._workers.clear()
            self._generators.clear()
            self._searchers.clear()
            self._meta.clear()
        for w in workers:
            w.shutdown()
