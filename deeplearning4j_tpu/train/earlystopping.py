"""Early stopping: epoch/iteration termination + best-model saving.

Parity: earlystopping/ in the reference — EarlyStoppingConfiguration,
trainer/BaseEarlyStoppingTrainer.java:52-113 (the epoch loop with
IterationTerminationCondition / EpochTerminationCondition checks),
termination/ (MaxEpochs, ScoreImprovementEpoch, BestScoreEpoch,
MaxTimeIteration, MaxScoreIteration, InvalidScoreIteration),
saver/ (LocalFileModelSaver, InMemoryModelSaver), scorecalc/
(DataSetLossCalculator).
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# Termination conditions
# ---------------------------------------------------------------------------


class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after ``patience`` epochs without ≥ min_improvement improvement."""

    def __init__(self, patience: int, min_improvement: float = 0.0):
        self.patience = patience
        self.min_improvement = min_improvement

    def initialize(self):
        self.best = math.inf
        self.best_epoch = -1

    def terminate(self, epoch, score):
        if score < self.best - self.min_improvement:
            self.best = score
            self.best_epoch = epoch
            return False
        return epoch - self.best_epoch >= self.patience

    def __str__(self):
        return (
            f"ScoreImprovementEpochTerminationCondition(patience={self.patience}, "
            f"minImprovement={self.min_improvement})"
        )


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score reaches a target value."""

    def __init__(self, best_expected: float):
        self.best_expected = best_expected

    def terminate(self, epoch, score):
        return score <= self.best_expected

    def __str__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected})"


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds

    def initialize(self):
        # monotonic: an NTP step must not shorten (or extend) the budget
        self._t0 = time.monotonic()

    def terminate(self, last_score):
        return time.monotonic() - self._t0 >= self.max_seconds

    def __str__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Divergence protection: stop if score explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score

    def __str__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop on NaN/Inf score."""

    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)

    def __str__(self):
        return "InvalidScoreIterationTerminationCondition()"


class MaxParamNormIterationTerminationCondition(IterationTerminationCondition):
    """Divergence protection on the PARAMETERS, not the score: stop once the
    global L2 norm of the model's parameters exceeds ``max_norm`` (or goes
    non-finite). A stable log-softmax loss cannot overflow, and a huge
    divergent step can even land a toy model on a perfect separator with
    score exactly 0.0 — the parameter norm is the signal that still
    explodes when the score cannot (docs/TEST_DEBT.md, divergence row).

    ``needs_model = True``: the iteration guard passes the live model so the
    norm is read from ``model.params``. One scalar host sync per iteration,
    on the early-stopping path only — never inside a traced step."""

    needs_model = True

    def __init__(self, max_norm: float):
        if not max_norm > 0:
            raise ValueError(f"max_norm must be positive, got {max_norm}")
        self.max_norm = max_norm

    def terminate(self, last_score, model=None):
        if model is None or getattr(model, "params", None) is None:
            return False
        sq = 0.0
        for leaf in jax.tree_util.tree_leaves(model.params):
            sq += float(jnp.sum(jnp.square(leaf.astype(jnp.float32))))
        norm = math.sqrt(sq) if math.isfinite(sq) else math.inf
        return norm > self.max_norm or not math.isfinite(norm)

    def __str__(self):
        return f"MaxParamNormIterationTerminationCondition({self.max_norm})"


# ---------------------------------------------------------------------------
# Score calculators
# ---------------------------------------------------------------------------


class ScoreCalculator:
    def calculate_score(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out set (scorecalc/DataSetLossCalculator)."""

    def __init__(self, data, batch_size: Optional[int] = None):
        self.data = data
        self.batch_size = batch_size

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.nn.model import MultiLayerNetwork, _iter_batches

        if isinstance(model, MultiLayerNetwork):
            total, n = 0.0, 0
            source = self.data() if callable(self.data) else self.data
            for x, y, fm, lm in _iter_batches(source, self.batch_size):
                b = len(x)
                total += model.score(x, y, fmask=fm, lmask=lm) * b
                n += b
            return total / max(n, 1)
        # ComputationGraph
        total, n = 0.0, 0
        source = self.data() if callable(self.data) else self.data
        for batch in model._iter_multi(source, self.batch_size):
            f = batch[0]
            b = f[0].shape[0]
            total += model.score(batch) * b
            n += b
        return total / max(n, 1)


class ClassificationScoreCalculator(ScoreCalculator):
    """negated accuracy/f1 so 'lower is better' holds
    (scorecalc/ClassificationScoreCalculator)."""

    def __init__(self, data, metric: str = "accuracy", batch_size: Optional[int] = None):
        self.data = data
        self.metric = metric
        self.batch_size = batch_size

    def calculate_score(self, model) -> float:
        ev = model.evaluate(self.data, batch_size=self.batch_size)
        return -float(getattr(ev, self.metric)())


# ---------------------------------------------------------------------------
# Model savers
# ---------------------------------------------------------------------------


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = model.clone()

    def save_latest_model(self, model, score):
        self._latest = model.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """Zip checkpoints in a directory (saver/LocalFileModelSaver.java)."""

    BEST = "bestModel.zip"
    LATEST = "latestModel.zip"

    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def save_best_model(self, model, score):
        from deeplearning4j_tpu.utils.serialization import save_network

        save_network(model, os.path.join(self.directory, self.BEST))

    def save_latest_model(self, model, score):
        from deeplearning4j_tpu.utils.serialization import save_network

        save_network(model, os.path.join(self.directory, self.LATEST))

    def get_best_model(self):
        from deeplearning4j_tpu.utils.serialization import restore_network

        p = os.path.join(self.directory, self.BEST)
        return restore_network(p) if os.path.exists(p) else None

    def get_latest_model(self):
        from deeplearning4j_tpu.utils.serialization import restore_network

        p = os.path.join(self.directory, self.LATEST)
        return restore_network(p) if os.path.exists(p) else None


# ---------------------------------------------------------------------------
# Configuration / result / trainer
# ---------------------------------------------------------------------------


@dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[EpochTerminationCondition] = field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = field(
        default_factory=list
    )
    score_calculator: Optional[ScoreCalculator] = None
    model_saver: Any = None
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclass
class EarlyStoppingResult:
    termination_reason: str          # "EpochTerminationCondition" | "IterationTerminationCondition" | "Error"
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


class EarlyStoppingTrainer:
    """Drives fit-epoch/evaluate/terminate (BaseEarlyStoppingTrainer:52-113).
    Works for MultiLayerNetwork and ComputationGraph."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_data,
                 batch_size: Optional[int] = None):
        self.config = config
        self.model = model
        self.train_data = train_data
        self.batch_size = batch_size
        if config.model_saver is None:
            config.model_saver = InMemoryModelSaver()

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        model = self.model
        if model.params is None:
            model.init()
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()

        score_vs_epoch = {}
        best_score = math.inf
        best_epoch = -1
        epoch = 0
        reason, details = "EpochTerminationCondition", ""

        class _IterGuard:
            """Listener that raises to abort mid-epoch on iteration
            termination (the reference checks inside the minibatch loop)."""

            class Stop(Exception):
                def __init__(self, cond):
                    self.cond = cond

            def __init__(self, conds):
                self.conds = conds

            def on_epoch_start(self, model, epoch):
                pass

            def on_epoch_end(self, model, epoch):
                pass

            def iteration_done(self, m, it, score, bs=0):
                for c in self.conds:
                    # conditions that inspect model state (param norm)
                    # declare needs_model; score-only conditions keep the
                    # reference signature
                    if getattr(c, "needs_model", False):
                        hit = c.terminate(score, model=m)
                    else:
                        hit = c.terminate(score)
                    if hit:
                        raise _IterGuard.Stop(c)

        guard = _IterGuard(cfg.iteration_termination_conditions)
        saved_listeners = list(model.listeners)
        if cfg.iteration_termination_conditions:
            model.listeners = saved_listeners + [guard]
        try:
            while True:
                try:
                    model.fit(self.train_data, epochs=1, batch_size=self.batch_size)
                except _IterGuard.Stop as s:
                    reason = "IterationTerminationCondition"
                    details = str(s.cond)
                    break

                if cfg.score_calculator is not None and (
                    epoch % max(cfg.evaluate_every_n_epochs, 1) == 0
                ):
                    score = cfg.score_calculator.calculate_score(model)
                else:
                    score = score_vs_epoch.get(epoch - 1, math.inf)
                score_vs_epoch[epoch] = score

                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best_model(model, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(model, score)

                stop = False
                for c in cfg.epoch_termination_conditions:
                    if c.terminate(epoch, score):
                        reason = "EpochTerminationCondition"
                        details = str(c)
                        stop = True
                        break
                epoch += 1
                if stop:
                    break
        finally:
            model.listeners = saved_listeners

        best_model = cfg.model_saver.get_best_model()
        if best_model is None:
            if score_vs_epoch:
                # no saver capture but epochs were scored: current model stands
                best_model = model
                best_epoch = epoch - 1
                best_score = score_vs_epoch.get(epoch - 1, math.inf)
            else:
                # terminated before ANY epoch completed (e.g. divergence mid
                # epoch 0): there is no best model — do not present the
                # possibly-NaN current weights as one
                best_model = None
                best_epoch = -1
                best_score = math.inf
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=score_vs_epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch,
            best_model=best_model,
        )


EarlyStoppingGraphTrainer = EarlyStoppingTrainer
