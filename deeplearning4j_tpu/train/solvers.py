"""Deterministic solvers: line search, conjugate gradient, L-BFGS.

Parity: optimize/Solver.java:50-80 (dispatch on OptimizationAlgorithm),
optimize/solvers/{BaseOptimizer.java:54, LineGradientDescent.java,
ConjugateGradient.java, LBFGS.java, BackTrackLineSearch.java}.

TPU-first: loss+gradient over the FLATTENED parameter vector is one jitted
value_and_grad executable (ravel_pytree); the two-loop L-BFGS recursion and
CG direction updates are tiny device-side vector ops; only the line-search
control flow (a handful of scalar comparisons per iteration) runs on the
host — versus the reference where every dot/axpy is a separate op dispatch.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.utils import bucketing


class BackTrackLineSearch:
    """Armijo backtracking (BackTrackLineSearch.java): shrink the step until
    f(x + a*d) <= f(x) + c1 * a * g.d."""

    def __init__(self, c1: float = 1e-4, rho: float = 0.5, max_iterations: int = 20,
                 initial_step: float = 1.0):
        self.c1 = c1
        self.rho = rho
        self.max_iterations = max_iterations
        self.initial_step = initial_step

    def search(self, f: Callable, x: jnp.ndarray, f0: float, g: jnp.ndarray,
               direction: jnp.ndarray) -> Tuple[float, float]:
        """Returns (alpha, f_new). alpha=0 if no improving step found."""
        slope = float(g @ direction)
        if slope >= 0:  # not a descent direction
            return 0.0, f0
        alpha = self.initial_step
        for _ in range(self.max_iterations):
            f_new = float(f(x + alpha * direction))
            if np.isfinite(f_new) and f_new <= f0 + self.c1 * alpha * slope:
                return alpha, f_new
            alpha *= self.rho
        return 0.0, f0


class Solver:
    """``Solver(model, algorithm).optimize(data, iterations)`` — full-batch
    deterministic optimization of a MultiLayerNetwork's loss.

    algorithm: "lbfgs" | "conjugate_gradient" | "line_gradient_descent".
    ``m`` is the L-BFGS history length (LBFGS.java's default secret: 4... we
    use the conventional 10).
    """

    def __init__(self, model, algorithm: str = "lbfgs", m: int = 10,
                 line_search: Optional[BackTrackLineSearch] = None):
        self.model = model
        self.algorithm = algorithm.lower()
        if self.algorithm not in ("lbfgs", "conjugate_gradient", "line_gradient_descent"):
            raise ValueError(f"unknown algorithm {self.algorithm!r}")
        self.m = m
        self.line_search = line_search or BackTrackLineSearch()
        self._vg = None
        self._f = None
        self._jf = None
        self._jvg = None

    # -- jitted loss over the flat vector ---------------------------------
    def _build(self, x, y, fm, lm):
        """Bind the batch to the (cached) jitted executables. The batch and
        mutable state are jit ARGUMENTS, not closure captures, so reusing
        the Solver across batches/epochs hits the jit cache instead of
        retracing (round-2 advisor finding)."""
        model = self.model
        flat0, unravel = ravel_pytree(model.params)
        if self._jvg is None:
            rngs = None  # deterministic objective: no dropout/noise streams

            def loss_flat(flat, state, xb, yb, fmb, lmb):
                # python body runs once per trace → counts actual compiles
                bucketing.telemetry().record_trace("solver", np.shape(xb))
                params = unravel(flat)
                loss, _ = model._loss(params, state, xb, yb, fmb, lmb, rngs,
                                      train=False)
                return loss

            self._jf = jax.jit(loss_flat)
            self._jvg = jax.jit(jax.value_and_grad(loss_flat))
        state = model.state
        self._f = lambda flat: self._jf(flat, state, x, y, fm, lm)
        self._vg = lambda flat: self._jvg(flat, state, x, y, fm, lm)
        return flat0, unravel

    def optimize(self, data, iterations: int = 100, tolerance: float = 1e-6) -> float:
        """Minimize over ``iterations`` solver steps; returns final loss and
        writes the optimized params back into the model."""
        from deeplearning4j_tpu.nn.model import _as_batch

        from deeplearning4j_tpu.nn.model import _cast_input, _cast_labels

        x, y, fm, lm = _as_batch(data)
        n = len(x)
        if bucketing.bucketing_enabled() and n > 0 and y is not None:
            # pad to the shared ladder so successive batches of nearby sizes
            # reuse one value_and_grad executable per bucket. The objective
            # is train=False (BN on running stats), so tiled pad rows only
            # need zero loss weight: the pre-scaled validity mask keeps the
            # loss the exact mean over the n real rows, and masked rows'
            # gradients vanish with their scores.
            target = bucketing.bucket_size(n)
            bucketing.telemetry().record_hit("solver", n, target)
            pad = target - n
            if pad:
                x = bucketing.tile_pad(x, pad)
                y = bucketing.tile_pad(y, pad)
                fm = bucketing.tile_pad(fm, pad)
                lm = bucketing.tile_pad(lm, pad) if lm is not None else None
            # uniform convention: the mask is always materialized, so full
            # and padded batches share one executable per bucket
            lm = bucketing.padded_label_mask(y, lm, n, force=True)
        x = _cast_input(x, self.model.dtype)
        y = _cast_labels(y, self.model.dtype)
        flat, unravel = self._build(x, y, fm, lm)

        f0, g = self._vg(flat)
        f0 = float(f0)
        if self.algorithm == "lbfgs":
            flat, f0 = self._lbfgs(flat, f0, g, iterations, tolerance)
        elif self.algorithm == "conjugate_gradient":
            flat, f0 = self._cg(flat, f0, g, iterations, tolerance)
        else:
            flat, f0 = self._gd(flat, f0, g, iterations, tolerance)
        self.model.params = unravel(flat)
        return f0

    # -- algorithms --------------------------------------------------------
    def _gd(self, x, f0, g, iterations, tol):
        for _ in range(iterations):
            d = -g
            alpha, f_new = self.line_search.search(self._f, x, f0, g, d)
            if alpha == 0.0 or f0 - f_new < tol:
                break
            x = x + alpha * d
            f0, g = self._vg(x)
            f0 = float(f0)
        return x, f0

    def _cg(self, x, f0, g, iterations, tol):
        d = -g
        for _ in range(iterations):
            alpha, f_new = self.line_search.search(self._f, x, f0, g, d)
            if alpha == 0.0 or f0 - f_new < tol:
                break
            x = x + alpha * d
            f_prev_g = g
            f0, g = self._vg(x)
            f0 = float(f0)
            # Polak-Ribiere+ with automatic restart (ConjugateGradient.java)
            beta = float(jnp.maximum(
                (g @ (g - f_prev_g)) / jnp.maximum(f_prev_g @ f_prev_g, 1e-12), 0.0
            ))
            d = -g + beta * d
            if float(g @ d) >= 0:  # not descent -> restart
                d = -g
        return x, f0

    def _lbfgs(self, x, f0, g, iterations, tol):
        s_hist: List[jnp.ndarray] = []
        y_hist: List[jnp.ndarray] = []
        rho_hist: List[float] = []
        for _ in range(iterations):
            d = self._two_loop(g, s_hist, y_hist, rho_hist)
            ls = BackTrackLineSearch(
                c1=self.line_search.c1, rho=self.line_search.rho,
                max_iterations=self.line_search.max_iterations,
                initial_step=1.0 if s_hist else min(1.0, 1.0 / max(float(jnp.linalg.norm(g)), 1e-12)),
            )
            alpha, f_new = ls.search(self._f, x, f0, g, d)
            if alpha == 0.0 or f0 - f_new < tol:
                break
            x_new = x + alpha * d
            _, g_new = self._vg(x_new)
            s = x_new - x
            yv = g_new - g
            sy = float(s @ yv)
            if sy > 1e-10:  # curvature condition
                s_hist.append(s)
                y_hist.append(yv)
                rho_hist.append(1.0 / sy)
                if len(s_hist) > self.m:
                    s_hist.pop(0)
                    y_hist.pop(0)
                    rho_hist.pop(0)
            x, f0, g = x_new, f_new, g_new
        return x, f0

    @staticmethod
    def _two_loop(g, s_hist, y_hist, rho_hist):
        """Standard L-BFGS two-loop recursion (LBFGS.java's implicit-Hessian
        direction); all ops are device-side vector math."""
        q = -g
        if not s_hist:
            return q
        alphas = []
        for s, yv, rho in zip(reversed(s_hist), reversed(y_hist), reversed(rho_hist)):
            a = rho * float(s @ q)
            alphas.append(a)
            q = q - a * yv
        gamma = float(s_hist[-1] @ y_hist[-1]) / max(float(y_hist[-1] @ y_hist[-1]), 1e-12)
        q = gamma * q
        for (s, yv, rho), a in zip(zip(s_hist, y_hist, rho_hist), reversed(alphas)):
            b = rho * float(yv @ q)
            q = q + (a - b) * s
        return q
