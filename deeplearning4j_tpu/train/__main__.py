"""Trainer CLI: ``python -m deeplearning4j_tpu.train``.

Reference parity: parallelism/main/ParallelWrapperMain.java (headless
training entry point driven by flags). Loads a model or configuration with
ModelGuesser semantics, trains on an .npz dataset or a built-in fetcher,
and writes a native checkpoint zip.

Examples::

    python -m deeplearning4j_tpu.train model_or_conf.json \
        --data train.npz --epochs 3 --batch-size 128 --output trained.zip
    python -m deeplearning4j_tpu.train lenet.json --dataset mnist --epochs 1
    python -m deeplearning4j_tpu.train conf.json --data d.npz --data-parallel
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.train",
        description="Train a model from a config JSON / model zip / Keras h5.")
    p.add_argument("model", help="configuration JSON, native/DL4J zip, or Keras h5")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--data", help=".npz file with arrays x and y "
                     "(optional fmask/lmask)")
    src.add_argument("--dataset", choices=["mnist", "emnist", "iris", "cifar10"],
                     help="built-in dataset fetcher")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--output", default="model.zip", help="checkpoint zip path")
    p.add_argument("--data-parallel", action="store_true",
                   help="shard batches over all local devices (ParallelWrapper)")
    p.add_argument("--listener-frequency", type=int, default=10,
                   help="score print frequency (iterations)")
    p.add_argument("--evaluate", action="store_true",
                   help="run classification evaluation after training")
    return p


def _load_model(path: str):
    from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.model import MultiLayerConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.utils.guesser import load_any

    obj = load_any(path)
    if isinstance(obj, MultiLayerConfiguration):
        return MultiLayerNetwork(obj).init()
    if isinstance(obj, ComputationGraphConfiguration):
        return ComputationGraph(obj).init()
    return obj  # already a model


def _load_data(args):
    if args.data:
        d = np.load(args.data)
        if "x" not in d or "y" not in d:
            raise SystemExit(f"{args.data}: expected arrays 'x' and 'y', "
                             f"found {sorted(d.files)}")
        fmask = d["fmask"] if "fmask" in d else None
        lmask = d["lmask"] if "lmask" in d else None
        if lmask is not None:
            return (d["x"], d["y"], fmask, lmask)
        if fmask is not None:
            return (d["x"], d["y"], fmask)
        return (d["x"], d["y"])
    from deeplearning4j_tpu.datasets.fetchers import (
        CifarDataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
        MnistDataSetIterator)

    it = {"mnist": MnistDataSetIterator, "emnist": EmnistDataSetIterator,
          "iris": IrisDataSetIterator, "cifar10": CifarDataSetIterator}[
              args.dataset](batch_size=args.batch_size)
    return it


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu.train.listeners import ScoreIterationListener
    from deeplearning4j_tpu.utils.serialization import save_network

    model = _load_model(args.model)
    if not hasattr(model, "fit"):
        raise SystemExit(f"{args.model} does not contain a trainable model")
    model.set_listeners(ScoreIterationListener(args.listener_frequency))
    data = _load_data(args)

    if args.data_parallel:
        from deeplearning4j_tpu.parallel import ParallelWrapper

        ParallelWrapper(model).fit(data, epochs=args.epochs,
                                   batch_size=args.batch_size)
    else:
        model.fit(data, epochs=args.epochs, batch_size=args.batch_size)

    save_network(model, args.output)
    print(f"saved {args.output}")

    if args.evaluate:
        ev = model.evaluate(data, batch_size=args.batch_size)
        print(ev.stats() if hasattr(ev, "stats") else ev)
    return 0


if __name__ == "__main__":
    sys.exit(main())
