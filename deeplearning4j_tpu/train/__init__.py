"""Training: updaters (optimizers), schedules, listeners, the fit loop.

TPU-native replacement for the reference's Solver/updater stack
(/root/reference/deeplearning4j-nn/.../optimize/Solver.java:50,
 nn/updater/BaseMultiLayerUpdater.java): instead of an iteration driver
mutating a flattened param view through per-block GradientUpdaters, the whole
``step(params, opt_state, batch) -> (params, opt_state, metrics)`` is a pure
function compiled once by XLA, with optimizer state as a pytree sharded
alongside the params.
"""

from deeplearning4j_tpu.train.updaters import (
    Updater,
    make_updater,
    normalize_updater,
    scale_lr,
    schedule_value,
)
from deeplearning4j_tpu.train.resilience import (
    ChaosInjector,
    ChaosPreemption,
    DivergenceError,
    DivergenceGuard,
    active_chaos,
    install_chaos,
    resume,
    save_checkpoint,
    validate_checkpoint,
)
from deeplearning4j_tpu.train.listeners import (
    BaseTrainingListener,
    CollectScoresListener,
    ComposedListener,
    PerformanceListener,
    ProfilerListener,
    ScoreIterationListener,
    TimeIterationListener,
    TrainingListener,
)
from deeplearning4j_tpu.train.checkpoint import Checkpoint, CheckpointListener
from deeplearning4j_tpu.train.earlystopping import (
    BestScoreEpochTerminationCondition,
    ClassificationScoreCalculator,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingGraphTrainer,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxParamNormIterationTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
__all__ = [
    "Updater",
    "make_updater",
    "normalize_updater",
    "scale_lr",
    "schedule_value",
    "ChaosInjector",
    "ChaosPreemption",
    "ElasticTrainer",
    "DivergenceError",
    "DivergenceGuard",
    "active_chaos",
    "install_chaos",
    "resume",
    "save_checkpoint",
    "validate_checkpoint",
    "TrainingListener",
    "BaseTrainingListener",
    "ProfilerListener",
    "ScoreIterationListener",
    "PerformanceListener",
    "CollectScoresListener",
    "TimeIterationListener",
    "ComposedListener",
    "Checkpoint",
    "CheckpointListener",
    "EarlyStoppingConfiguration",
    "EarlyStoppingResult",
    "EarlyStoppingTrainer",
    "EarlyStoppingGraphTrainer",
    "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition",
    "MaxTimeIterationTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "MaxParamNormIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "DataSetLossCalculator",
    "ClassificationScoreCalculator",
    "InMemoryModelSaver",
    "LocalFileModelSaver",
]


def __getattr__(name):
    # lazy: train.elastic pulls in the whole parallel package, whose wrapper
    # module reaches back into nn.model — eager import here would cycle
    if name == "ElasticTrainer":
        from deeplearning4j_tpu.train.elastic import ElasticTrainer

        return ElasticTrainer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
