"""Layerwise unsupervised pretraining (VAE ELBO, denoising autoencoder).

Capability parity with the reference's pretrain path
(MultiLayerNetwork.pretrain / pretrainLayer — the Solver drives a
pretrainable layer's own score; gradientcheck/GradientCheckUtil.java:512
checks it). TPU-first: each layer's pretrain objective is one jitted step
over (that layer's params) with earlier layers applied inference-mode as a
fixed featurizer.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.model import MultiLayerNetwork, _iter_batches
from deeplearning4j_tpu.train.updaters import make_updater, normalize_updater


def _pretrain_loss(layer, params, x, rng):
    """Dispatch to the layer's unsupervised objective."""
    if hasattr(layer, "elbo_loss"):  # VariationalAutoencoder
        return layer.elbo_loss(params, x, rng)
    if hasattr(layer, "reconstruct"):  # AutoEncoder: denoising MSE
        rec = layer.reconstruct(params, x, rng=rng, corrupt=True)
        return jnp.mean(jnp.sum((rec - x) ** 2, axis=-1))
    raise ValueError(f"Layer {layer._type_name} is not pretrainable")


def is_pretrainable(layer) -> bool:
    return hasattr(layer, "elbo_loss") or hasattr(layer, "reconstruct")


def pretrain_layer(model: MultiLayerNetwork, layer_idx: int, data,
                   epochs: int = 1, batch_size: Optional[int] = None,
                   updater=None) -> MultiLayerNetwork:
    """Unsupervised-train ONE layer; earlier layers featurize inference-mode
    (MultiLayerNetwork.pretrainLayer equivalent)."""
    layer = model.layers[layer_idx]
    if not is_pretrainable(layer):
        raise ValueError(f"layer {layer_idx} ({layer._type_name}) is not pretrainable")
    upd = make_updater(normalize_updater(updater or model.conf.updater))
    opt_state = upd.init(model.params[layer_idx])

    def step(lparams, opt_state, it, rng, x):
        def loss_fn(p):
            return _pretrain_loss(layer, p, x, rng)

        loss, grads = jax.value_and_grad(loss_fn)(lparams)
        delta, new_opt = upd.update(grads, opt_state, lparams, it)
        new_params = jax.tree_util.tree_map(lambda p, d: p - d, lparams, delta)
        return new_params, new_opt, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    # Copy the layer's params before they enter the donated step chain:
    # lparams aliases model.params[layer_idx], and the first dispatch would
    # otherwise invalidate the buffer still reachable through model.params
    # (read every iteration by the _forward featurizer below).
    lparams = jax.tree_util.tree_map(jnp.copy, model.params[layer_idx])
    it = 0
    for _ in range(epochs):
        source = data() if callable(data) else data
        for x, _, _, _ in _iter_batches(source, batch_size):
            # featurize through the preceding stack, no state updates
            feats, _, _, _, _ = model._forward(
                model.params, model.state, x, train=False, rngs=None, upto=layer_idx
            )
            lparams, opt_state, loss = jstep(
                lparams, opt_state, jnp.asarray(it, jnp.int32), model._next_rng(), feats
            )
            it += 1
    model.params = model.params[:layer_idx] + (lparams,) + model.params[layer_idx + 1:]
    return model


def pretrain(model: MultiLayerNetwork, data, epochs: int = 1,
             batch_size: Optional[int] = None, updater=None) -> MultiLayerNetwork:
    """Greedy layerwise pretraining over every pretrainable layer, in order
    (MultiLayerNetwork.pretrain equivalent)."""
    if model.params is None:
        model.init()
    for i, layer in enumerate(model.layers):
        if is_pretrainable(layer):
            pretrain_layer(model, i, data, epochs=epochs, batch_size=batch_size,
                           updater=updater)
    return model
