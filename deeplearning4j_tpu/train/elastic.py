"""Elastic multi-host data-parallel training on the membership runtime.

``ElasticTrainer`` runs synchronous data-parallel SGD across N worker
*processes* — each its own single-process JAX instance (dense collectives
stay inside the process/slice where XLA is optimal) — exchanging explicit
gradient payloads through the ``parallel/elastic.py`` :class:`FileStore`
(the DCN stand-in; optionally ternary-compressed per PR 3). When a worker's
lease lapses mid-epoch the survivors drain to the step boundary, re-form at
the reduced world size — re-sharding the arXiv 2004.13336 optimizer-state
segments — and keep training; the preempted worker rejoins through a live
handoff or the distributed checkpoint layout (per-host shards + CRC'd
manifest, ``train/resilience.py``).

Three design decisions make elasticity *bit-exact* rather than merely
tolerant (tests/test_elastic.py asserts equality, not closeness):

- **Virtual shards.** The global batch of every step is split into ``v``
  fixed-shape padded micro-shards (``v`` frozen at bootstrap), and vshard
  ``j`` of step ``s`` draws RNG ``fold_in(base, s*v + j)``. Membership only
  decides WHICH worker computes a vshard (``j % world``), never the
  vshard's data, shape, rng, or weight — so the fixed-order payload sum is
  bitwise invariant under shrink/grow, and a killed-worker run lands on
  exactly the uninterrupted run's parameters.
- **Segmented optimizer state with a buddy mirror.** Eligible layers'
  optimizer stats live as flat per-rank segments (each worker updates 1/W
  of the vector); worker ``r`` additionally maintains rank ``(r+1) % W``'s
  segments, so a single worker's death loses nothing: the buddy serves the
  dead rank's updated params mid-step and its optimizer segments at the
  re-form handoff. Layers with gradient normalization, constraints, or
  mixed dtypes fall back to dense replicated updates (same rule as
  ``parallel/grads.py``).
- **Step-boundary reconfiguration.** Membership changes surface as
  :class:`MembershipChanged` and are handled only between steps: survivors
  re-publish state under the new generation, re-slice segments, and re-run
  the interrupted step at the reduced world — nothing is half-applied.

The CLI (``python -m deeplearning4j_tpu.train.elastic worker|launch``)
drives the synthetic workload used by tests/test_elastic.py and
tools/elastic_smoke.sh: ``launch`` supervises N local worker processes and
can relaunch killed ones (the rejoin path).
"""

from __future__ import annotations

import argparse
import io
import json
import math
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.obs import fleet
from deeplearning4j_tpu.parallel import compress as compression
from deeplearning4j_tpu.parallel.elastic import (
    ElasticRuntime,
    FileStore,
    MembershipChanged,
    View,
)
from deeplearning4j_tpu.parallel.grads import _flat, _unflat
from deeplearning4j_tpu.parallel.netstore import open_store
from deeplearning4j_tpu.train import resilience
from deeplearning4j_tpu.train.updaters import apply_gradient_normalization
from deeplearning4j_tpu.utils import bucketing

__all__ = ["ElasticTrainer", "mirror_ranks"]


def mirror_ranks(t: int, W: int, R: int,
                 racks: Sequence[str] = ()) -> List[int]:
    """Ranks holding mirrors of rank ``t``'s optimizer segments under
    replication factor ``R`` (owner + R-1 mirrors, capped at the world
    size) with rack-aware placement: candidates in OTHER racks than the
    owner's sort first, ties broken by ring distance ``(t - r) % W`` —
    nearest predecessor first. With uniform racks and R=2 this is exactly
    the classic buddy (the mirror of ``t`` sits at ``(t-1) % W``, i.e.
    worker ``r`` mirrors rank ``(r+1) % W``), which keeps the R=2 layout —
    and with it every existing checkpoint shard and bit-exactness gate —
    unchanged. Deterministic in its inputs, so every member derives the
    same placement from the view's recorded rack labels."""
    W = int(W)
    R = min(int(R), W)
    if R <= 1 or W <= 1:
        return []
    owner_rack = racks[t] if t < len(racks) else ""
    return sorted(
        (r for r in range(W) if r != t),
        key=lambda r: ((racks[r] if r < len(racks) else "") == owner_rack,
                       (t - r) % W))[:R - 1]


# ---------------------------------------------------------------------------
# npz framing for store payloads
# ---------------------------------------------------------------------------


def _pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _unpack_arrays(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _json_to_array(value: dict) -> np.ndarray:
    return np.frombuffer(json.dumps(value).encode("utf-8"), np.uint8)


def _array_to_json(arr: np.ndarray) -> dict:
    return json.loads(arr.tobytes().decode("utf-8"))


# ---------------------------------------------------------------------------
# Exchange plan (per-layer), mirroring parallel/grads.py eligibility
# ---------------------------------------------------------------------------


@dataclass
class _Entry:
    key: int
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    n: int
    dtype: Any
    mode: str                  # "flat" | "dense"
    updater: Any
    cfg: Any


class _JobDone(Exception):
    """Internal: the job completed while this worker was expelled; the final
    state was adopted from the ``done`` blob rank 0 leaves in the store."""


class _Prefetcher:
    """Asynchronous DCN payload fetch: polls the store for a set of keys
    from a daemon thread so the fetch overlaps with in-process compute (my
    own vshard backward passes, the dense update) instead of serializing
    behind it at the boundary wait. ``drain()`` hands finished payloads to
    the consumer; the boundary wait only blocks on whatever the overlap
    didn't already cover — that residue is the measured
    ``dl4j_elastic_boundary_stall_seconds``. Purely an ordering
    optimization: payload bytes and the fixed-order combine are untouched,
    so bit-exactness is unaffected (``DL4J_TPU_ELASTIC_ASYNC=0`` falls back
    to the synchronous fetch)."""

    def __init__(self, store, keys: Dict[Any, str], poll: float):
        self.store = store
        self._pending = dict(keys)
        self.poll = float(poll)
        self._lock = threading.Lock()
        self._got: Dict[Any, Dict[str, np.ndarray]] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="elastic-prefetch", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while self._pending and not self._stop.is_set():
            for ident, key in list(self._pending.items()):
                if self._stop.is_set():
                    return
                try:
                    data = self.store.get(key)
                    arrays = (None if data is None
                              else _unpack_arrays(data))
                except (OSError, ValueError):
                    return  # store gone / garbage: the sync path takes over
                if arrays is not None:
                    with self._lock:
                        self._got[ident] = arrays
                    del self._pending[ident]
            if self._pending:
                self._stop.wait(self.poll)

    def drain(self) -> Dict[Any, Dict[str, np.ndarray]]:
        with self._lock:
            got = dict(self._got)
            self._got.clear()
        return got

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


class ElasticTrainer:
    """Synchronous elastic data-parallel trainer for a MultiLayerNetwork."""

    def __init__(self, model, store_dir, worker_id: str, *, world: int = 2,
                 vshards: Optional[int] = None, compress: bool = False,
                 threshold: float = 1e-3, ckpt_dir=None, ckpt_every: int = 0,
                 ttl: Optional[float] = None, poll: Optional[float] = None,
                 replication: Optional[int] = None,
                 rack: Optional[str] = None, slice_spec=None,
                 async_exchange: Optional[bool] = None):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(model, ComputationGraph):
            raise NotImplementedError(
                "ElasticTrainer drives MultiLayerNetwork models; wrap CG "
                "training in the single-host paths meanwhile")
        if model.params is None:
            model.init()
        self.model = model
        self.store = open_store(store_dir)
        self.wid = str(worker_id)
        self.world = int(world)
        self.vshards = None if vshards is None else int(vshards)
        self.compress = bool(compress)
        self.threshold = float(threshold)
        self.ckpt_dir = None if ckpt_dir is None else os.fspath(ckpt_dir)
        self.ckpt_every = int(ckpt_every)
        self.replication = max(1, int(
            os.environ.get("DL4J_TPU_ELASTIC_MIRRORS", "2")
            if replication in (None, 0) else replication))
        self.async_exchange = bool(
            os.environ.get("DL4J_TPU_ELASTIC_ASYNC", "1") != "0"
            if async_exchange is None else async_exchange)
        if slice_spec:
            from deeplearning4j_tpu.parallel.mesh_step import MeshSlice

            self.slice: Optional[Any] = MeshSlice(slice_spec)
        else:
            self.slice = None
        self.rt = ElasticRuntime(self.store, self.wid, ttl=ttl, poll=poll,
                                 rack=rack)
        obs.gauge("dl4j_mirror_replication_factor",
                  "Configured optimizer-segment replication factor R "
                  "(owner + R-1 mirrors, capped at world size)").set(
                      self.replication)
        self.stall_s = 0.0   # cumulative boundary time blocked on payloads
        # fleet observability: slice identity on every span/event, per-rank
        # step-wall skew detection (rank 0 evaluates), snapshot publication
        # throttle (report-time: at most ~1/s into the store)
        if self.slice is not None:
            obs.set_process_context(slice=str(slice_spec))
        self._straggler = fleet.StragglerDetector()
        self._stragglers: set = set()
        self._last_publish = 0.0
        self._build_plan()
        _, self._bwd, _ = model._get_phase_fns()
        self._base_rng = model._rng
        # formed state: segment stats per flat entry {key: {rank: [S, m]}},
        # dense structured opt per dense entry, residuals per owned vshard
        self._segs: Dict[int, Dict[int, np.ndarray]] = {}
        self._dense_opt: Dict[int, Any] = {}
        self._residuals: Dict[int, np.ndarray] = {}
        self._m: Dict[int, int] = {}
        self._formed = False
        self.losses: List[float] = []
        self.epoch = 0
        self.step_in_epoch = 0
        self._steps_per_epoch = 0

    # -- plan ---------------------------------------------------------------
    def _build_plan(self):
        model = self.model
        entries: Dict[int, _Entry] = {}
        order = list(range(len(model.layers)))
        for key in order:
            p = model.params[key]
            leaves, treedef = jax.tree_util.tree_flatten(p)
            if not leaves:
                continue
            cfg = model.layers[key]
            n = sum(int(np.prod(l.shape)) for l in leaves)
            dtypes = {jnp.dtype(l.dtype) for l in leaves}
            uniform_float = (len(dtypes) == 1 and
                             jnp.issubdtype(next(iter(dtypes)), jnp.floating))
            gn = getattr(cfg, "gradient_normalization", None)
            constraints = getattr(cfg, "constraints", None)
            eligible = uniform_float and not gn and not constraints
            entries[key] = _Entry(
                key=key, treedef=treedef,
                shapes=tuple(tuple(l.shape) for l in leaves), n=n,
                dtype=(next(iter(dtypes)) if uniform_float else None),
                mode="flat" if eligible else "dense",
                updater=model._updaters[key], cfg=cfg)
        self._entries = entries
        self._order = order
        self._flat_keys = [k for k in order
                           if k in entries and entries[k].mode == "flat"]
        self._dense_keys = [k for k in order
                            if k in entries and entries[k].mode == "dense"]
        self._total_n = sum(entries[k].n for k in self._flat_keys)

    def _stat_template(self, e: _Entry, m: int):
        template = e.updater.init(jnp.zeros((m,), e.dtype))
        leaves, tdef = jax.tree_util.tree_flatten(template)
        return len(leaves), tdef

    # -- structured <-> flat optimizer stats --------------------------------
    def _stats_full_from_structured(self, e: _Entry, structured,
                                    length: int) -> np.ndarray:
        """Per-layer structured opt state -> ``[n_stats, length]`` float
        stack (outer-stat-major leaf grouping, same layout as
        ``DataParallelStep._to_flat_opt``)."""
        leaves = jax.tree_util.tree_leaves(structured)
        n_inner = len(e.shapes)
        if leaves and len(leaves) % n_inner != 0:
            raise ValueError(
                f"opt state for layer {e.key} has {len(leaves)} leaves, not "
                f"a multiple of the {n_inner} param leaves")
        stats = []
        for i in range(0, len(leaves), n_inner):
            chunk = leaves[i:i + n_inner]
            flat = np.concatenate(
                [np.ravel(np.asarray(l)) for l in chunk])  # graftlint: disable=host-sync
            row = np.zeros((length,), flat.dtype)
            row[:e.n] = flat
            stats.append(row)
        if not stats:
            return np.zeros((0, length), np.dtype(e.dtype))
        return np.stack(stats)

    def _stats_structured_from_full(self, e: _Entry, full: np.ndarray):
        """Inverse: ``[n_stats, >=n]`` stack -> the model's structured
        per-layer opt state."""
        _, tdef = self._stat_template(e, int(full.shape[1]) if full.size
                                      else e.n)
        subtrees = []
        for row in full:
            subtrees.append(_unflat(jnp.asarray(row[:e.n]), e))
        return jax.tree_util.tree_unflatten(tdef, subtrees)

    # -- vshard / mirror geometry --------------------------------------------
    def _view_racks(self, view: View, prev: bool = False) -> List[str]:
        members = view.prev_members if prev else view.members
        labels = view.prev_racks if prev else view.racks
        return [labels.get(m, "") for m in members]

    def _held_ranks(self, rank: int, W: int,
                    racks: Sequence[str] = ()) -> List[int]:
        """Segments this worker carries: its primary plus every rank whose
        R-way rack-aware mirror set includes it (R=2, uniform racks ⇒ the
        classic ``[rank, (rank+1) % W]`` buddy pair)."""
        return [rank] + [t for t in range(W) if t != rank
                         and rank in mirror_ranks(
                             t, W, self.replication, racks)]

    def _vshard_owner(self, j: int) -> int:
        return j % self.rt.view.world

    def _my_vshards(self) -> List[int]:
        r = self.rt.view.rank_of(self.wid)
        W = self.rt.view.world
        return [j for j in range(self.vshards) if j % W == r]

    # -- forming / re-forming ------------------------------------------------
    def _slice_segs_from_full(self, full_by_key: Dict[int, np.ndarray],
                              view: View):
        """(Re-)slice my primary + R-way mirror segments for the new world
        out of the full per-layer stat stacks."""
        W = view.world
        r = view.rank_of(self.wid)
        held = self._held_ranks(r, W, self._view_racks(view))
        segs: Dict[int, Dict[int, np.ndarray]] = {}
        m_of: Dict[int, int] = {}
        for key in self._flat_keys:
            e = self._entries[key]
            m = -(-e.n // W)
            m_of[key] = m
            full = full_by_key[key]
            n_pad = m * W
            padded = np.zeros((full.shape[0], n_pad), full.dtype)
            padded[:, :min(full.shape[1], n_pad)] = full[:, :n_pad]
            segs[key] = {t: padded[:, t * m:(t + 1) * m].copy()
                         for t in held}
        self._segs = segs
        self._m = m_of

    def _form_fresh(self, view: View):
        """Bootstrap form: every worker derives identical state from the
        (seed-deterministic) model init — no handoff needed."""
        model = self.model
        full = {}
        for key in self._flat_keys:
            e = self._entries[key]
            full[key] = self._stats_full_from_structured(
                e, model.opt_state[key], e.n)
        self._slice_segs_from_full(full, view)
        self._dense_opt = {k: model.opt_state[k] for k in self._dense_keys}
        self._residuals = {j: np.zeros(self._total_n, np.float32)
                           for j in range(self.vshards)
                           if self._vshard_owner(j) == view.rank_of(self.wid)}
        self._formed = True

    def _form_from_checkpoint(self, view: View, ckpt: dict) -> bool:
        """Full-group restart: rebuild params/opt/position from the newest
        valid distributed checkpoint (``resilience.load_distributed_...``)."""
        man = ckpt["manifest"]
        pa = ckpt["params"]
        model = self.model
        # params + dense opt + layer state + meta
        meta = _array_to_json(pa["meta"])
        params = []
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                params.append(model.params[key])
                continue
            leaves = [jnp.asarray(pa[f"p{key}_{li}"])
                      for li in range(len(e.shapes))]
            params.append(jax.tree_util.tree_unflatten(e.treedef, leaves))
        model.params = tuple(params)
        for key in self._dense_keys:
            e = self._entries[key]
            n_leaves = len(jax.tree_util.tree_leaves(model.opt_state[key]))
            leaves = [jnp.asarray(pa[f"o{key}_{li}"])
                      for li in range(n_leaves)]
            tdef = jax.tree_util.tree_structure(model.opt_state[key])
            self._dense_opt[key] = jax.tree_util.tree_unflatten(tdef, leaves)
        st_leaves = jax.tree_util.tree_leaves(model.state)
        st_def = jax.tree_util.tree_structure(model.state)
        model.state = jax.tree_util.tree_unflatten(
            st_def, [jnp.asarray(pa[f"st{li}"])
                     for li in range(len(st_leaves))])
        self._base_rng = jnp.asarray(
            np.asarray(meta["base_rng"],
                       dtype=np.dtype(meta["base_rng_dtype"])))
        model.iteration = int(meta["iteration"])
        self.epoch, self.step_in_epoch = int(meta["epoch"]), int(meta["step"])
        self.losses = [float(v) for v in meta.get("losses", [])]
        # optimizer segments: assemble the full stacks from the per-host
        # shard files (each carries primary + mirror; any host can serve a
        # straggler's shard), then re-slice for the new world
        W_old = int(man["world"])
        full = {}
        for key in self._flat_keys:
            e = self._entries[key]
            full[key] = self._assemble_full_stats(
                e, W_old, lambda t: self._ckpt_seg(ckpt, key, t))
            if full[key] is None:
                return False
        self._slice_segs_from_full(full, view)
        self._restore_residuals(
            view, lambda j: self._ckpt_res(ckpt, W_old, j))
        self._formed = True
        obs.event("elastic_restart_restore", manifest=ckpt["path"],
                  iteration=model.iteration, epoch=self.epoch,
                  step=self.step_in_epoch)
        return True

    def _ckpt_seg(self, ckpt, key, t):
        for arrays in ckpt["shards"].values():
            a = arrays.get(f"k{key}_t{t}")
            if a is not None:
                return a
        return None

    def _ckpt_res(self, ckpt, W_old, j):
        arrays = ckpt["shards"].get(j % W_old, {})
        return arrays.get(f"res{j}")

    def _assemble_full_stats(self, e: _Entry, W_old: int, seg_of):
        """Rebuild one layer's full ``[n_stats, m_old * W_old]`` stat stack
        from per-rank segment sources (handoff files or checkpoint shards);
        ``seg_of(t)`` returns rank ``t``'s segment from primary or mirror,
        or None when unrecoverable."""
        m_old = -(-e.n // W_old)
        n_stats, _ = self._stat_template(e, m_old)
        full = np.zeros((n_stats, m_old * W_old), np.dtype(e.dtype))
        for t in range(W_old):
            seg = seg_of(t)
            if seg is None:
                obs.event("elastic_segment_unrecoverable", layer=e.key,
                          rank=t, world=W_old)
                return None
            full[:, t * m_old:(t + 1) * m_old] = seg
        return full

    def _restore_residuals(self, view: View, res_of):
        """Residuals move with vshard ownership; a dead worker's pending
        sub-threshold gradient mass is lost (zeros) — the documented,
        tolerance-bounded cost of compressed elasticity."""
        r = view.rank_of(self.wid)
        W = view.world
        res: Dict[int, np.ndarray] = {}
        for j in range(self.vshards):
            if j % W != r:
                continue
            a = res_of(j)
            res[j] = (np.zeros(self._total_n, np.float32) if a is None
                      else np.asarray(a, np.float32).copy())
        self._residuals = res

    # -- reform (handoff) ----------------------------------------------------
    def _reform(self, view: View):
        """Adopt ``view`` and re-form training state at its world size,
        looping through any further churn that lands mid-handoff."""
        while True:
            try:
                self._do_reform(view)
                return
            except MembershipChanged as mc:
                view = mc.view

    def _do_reform(self, view: View):
        self.rt.adopt(view)
        if self.wid not in view.members:
            # expelled (partition outlived the TTL): wait for the survivors
            # to grow the view back around our renewed lease, then take the
            # handoff as a joiner. If the job finishes first (rank 0 leaves
            # the terminal `done` blob), adopt that final state instead.
            self._formed = False
            view = self.rt.await_readmission(
                should_stop=lambda: self.store.exists("done"))
            if view is None:
                self._adopt_done()
                raise _JobDone()
            raise MembershipChanged(view)
        if self.vshards is None:
            self.vshards = max(view.world, 1)
        holders = view.holders()
        if not holders:
            # bootstrap or full-group restart: no live state to hand off
            ckpt = (resilience.load_distributed_checkpoint(self.ckpt_dir)
                    if self.ckpt_dir else None)
            if ckpt is not None and self._form_from_checkpoint(view, ckpt):
                return
            if view.reason == "restart":
                obs.event("elastic_restart_fresh", gen=view.gen)
            self._sync_to(view)
            self._form_fresh(view)
            return
        g = view.gen
        am_holder = self.wid in holders and self._formed
        if am_holder:
            self._publish_handoff(view)
        full, hands = self._await_handoff(view)
        meta = _array_to_json(full["meta"])
        model = self.model
        # adopt the coordinator's full copy (identical to a survivor's own
        # state; REQUIRED for a joiner)
        params = []
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                params.append(model.params[key])
                continue
            leaves = [jnp.asarray(full[f"p{key}_{li}"])
                      for li in range(len(e.shapes))]
            params.append(jax.tree_util.tree_unflatten(e.treedef, leaves))
        model.params = tuple(params)
        for key in self._dense_keys:
            n_leaves = len(jax.tree_util.tree_leaves(model.opt_state[key]))
            tdef = jax.tree_util.tree_structure(model.opt_state[key])
            self._dense_opt[key] = jax.tree_util.tree_unflatten(
                tdef, [jnp.asarray(full[f"o{key}_{li}"])
                       for li in range(n_leaves)])
        st_def = jax.tree_util.tree_structure(model.state)
        n_st = len(jax.tree_util.tree_leaves(model.state))
        model.state = jax.tree_util.tree_unflatten(
            st_def, [jnp.asarray(full[f"st{li}"]) for li in range(n_st)])
        self._base_rng = jnp.asarray(
            np.asarray(meta["base_rng"],
                       dtype=np.dtype(meta["base_rng_dtype"])))
        self.losses = [float(v) for v in meta.get("losses", [])]
        self._sync_to(view)
        # optimizer segments: primary from the old owner's hand file, buddy
        # mirror from its neighbor when the owner died, then re-slice
        W_old = len(view.prev_members)
        full_stats = {}
        for key in self._flat_keys:
            full_stats[key] = self._assemble_full_stats(
                self._entries[key], W_old,
                lambda t, k=key: self._hand_seg(hands, view, k, t))
            if full_stats[key] is None:
                raise RuntimeError(
                    f"elastic reform gen {g}: layer {key} optimizer "
                    "segments unrecoverable (owner and mirror both lost, "
                    "no checkpoint)")
        self._slice_segs_from_full(full_stats, view)
        self._restore_residuals(
            view, lambda j: self._hand_res(hands, view, j))
        self._formed = True

    def _sync_to(self, view: View):
        self.model.iteration = int(view.iteration)
        self.epoch = int(view.epoch)
        self.step_in_epoch = int(view.step)

    def _hand_seg(self, hands, view: View, key: int, t: int):
        """Rank ``t``'s outgoing segment from its old owner or ANY of its
        old mirrors (R-way, in the previous view's geometry)."""
        prev = view.prev_members
        sources = [t] + mirror_ranks(t, len(prev), self.replication,
                                     self._view_racks(view, prev=True))
        for s in sources:
            a = hands.get(prev[s], {}).get(f"k{key}_t{t}")
            if a is not None:
                return a
        return None

    def _hand_res(self, hands, view: View, j: int):
        prev = view.prev_members
        owner = prev[j % len(prev)] if prev else None
        if owner is None:
            return None
        return hands.get(owner, {}).get(f"res{j}")

    def _publish_handoff(self, view: View):
        g = view.gen
        arrays = {}
        for key in self._flat_keys:
            for t, seg in self._segs[key].items():
                arrays[f"k{key}_t{t}"] = seg
        for j, res in self._residuals.items():
            arrays[f"res{j}"] = res
        self.store.set(f"hand/{g}/{self.wid}", _pack_arrays(arrays))
        if view.holders()[0] != self.wid:
            return
        model = self.model
        full: Dict[str, np.ndarray] = {}
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                continue
            for li, leaf in enumerate(
                    jax.tree_util.tree_leaves(model.params[key])):
                full[f"p{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        for key in self._dense_keys:
            for li, leaf in enumerate(
                    jax.tree_util.tree_leaves(self._dense_opt[key])):
                full[f"o{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        for li, leaf in enumerate(jax.tree_util.tree_leaves(model.state)):
            full[f"st{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        rng = np.asarray(self._base_rng)  # graftlint: disable=host-sync
        full["meta"] = _json_to_array({
            "iteration": int(model.iteration), "epoch": self.epoch,
            "step": self.step_in_epoch,
            "base_rng": rng.tolist(), "base_rng_dtype": str(rng.dtype),
            "losses": [float(v) for v in self.losses],
            "vshards": int(self.vshards),
        })
        self.store.set(f"hand/{g}/full", _pack_arrays(full))

    def _await_handoff(self, view: View):
        g = view.gen
        holders = list(view.holders())
        want = {wid: f"hand/{g}/{wid}" for wid in holders}
        want["__full__"] = f"hand/{g}/full"
        got: Dict[str, Dict[str, np.ndarray]] = {}
        deadline = time.monotonic() + self.rt.wait_timeout
        while want:
            for wid, key in list(want.items()):
                data = self.store.get(key)
                if data is not None:
                    got[wid] = _unpack_arrays(data)
                    del want[wid]
            if not want:
                break
            self.rt.check_for_change()
            dead = [wid for wid in want if wid != "__full__"
                    and not self.rt.member_alive(wid)]
            if dead or ("__full__" in want and holders
                        and not self.rt.member_alive(holders[0])):
                # a holder died mid-handoff (the coordinator, if the full
                # copy is missing): shrink again and retry at the new view
                self.rt.report_dead(dead or [holders[0]],
                                    (view.epoch, view.step, view.iteration))
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic handoff gen {g}: still waiting on "
                    f"{sorted(want)} after {self.rt.wait_timeout:.0f}s")
            time.sleep(self.rt.poll)
        full = got.pop("__full__")
        return full, got

    # -- the step ------------------------------------------------------------
    def _chaos_hooks(self, it: int, rank: int):
        chaos = resilience.active_chaos()
        if chaos is None:
            return
        chaos.maybe_host_kill(it, rank=rank)
        # elastic-of-slices: this member process IS its slice, so a slice
        # preemption is one SIGKILL here (elastic rank == slice index)
        chaos.maybe_slice_kill(it, slice_index=rank)
        secs = chaos.partition_seconds(it, rank=rank)
        if secs > 0:
            # the net_partition fault: stop heartbeating and stall — to the
            # group this worker is on the wrong side of a switch. A stall
            # longer than the TTL gets us expelled; on waking we renew the
            # lease and rejoin through the handoff.
            self.rt.membership.suspend(secs + self.rt.ttl)
            obs.event("elastic_partition_begin", wid=self.wid, rank=rank,
                      iteration=it, seconds=secs)
            time.sleep(secs)
            self.rt.membership.heartbeat_now()
            obs.event("elastic_partition_end", wid=self.wid, rank=rank,
                      iteration=it)
        rsecs = chaos.rack_partition_seconds(it, rack=self.rt.rack)
        if rsecs > 0:
            # rack_partition: same mechanics, rack-wide blast radius — every
            # worker with the matching DL4J_TPU_RACK label goes dark at once
            self.rt.membership.suspend(rsecs + self.rt.ttl)
            obs.event("rack_partition", phase="begin", wid=self.wid,
                      rack=self.rt.rack, rank=rank, iteration=it,
                      seconds=rsecs)
            time.sleep(rsecs)
            self.rt.membership.heartbeat_now()
            obs.event("rack_partition", phase="end", wid=self.wid,
                      rack=self.rt.rack, rank=rank, iteration=it)
        chaos.maybe_preempt(it)
        chaos.maybe_slow(it, rank=rank)

    def _vshard_payload(self, j: int, xb, yb, it: int):
        """Compute vshard ``j``'s weighted contribution and frame it for the
        store. Weights (``n_j / N``) and rng depend only on (step, j) — the
        membership-invariance that makes elastic runs bit-exact."""
        from deeplearning4j_tpu.nn.model import _cast_input, _cast_labels

        model = self.model
        v = self.vshards
        c = self._vshard_rows
        lo, hi = j * c, min((j + 1) * c, len(xb))
        n_j = max(hi - lo, 0)
        if n_j <= 0:
            return _pack_arrays({"n": np.asarray(0, np.int64)})
        N = len(xb)
        w = np.float32(n_j) / np.float32(N)
        x_j, y_j, fm, lm, ew = bucketing.pad_fit_batch(
            xb[lo:hi], yb[lo:hi], None, None, c, site="elastic.fit")
        rng_j = jax.random.fold_in(self._base_rng, it * v + j)
        x_c = _cast_input(x_j, model.dtype)
        y_c = _cast_labels(y_j, model.dtype)
        fm_c = jnp.asarray(fm, model.dtype) if fm is not None else None
        lm_c = jnp.asarray(lm, model.dtype) if lm is not None else None
        ew_c = jnp.asarray(ew, model.dtype) if ew is not None else None
        if self.slice is not None:
            # elastic-of-slices: the vshard's backward runs GSPMD-sharded
            # over this member's (d,t,s) mesh — batch over the data axis,
            # params/state replicated, XLA inserting the in-slice
            # collectives (padded vshard rows are a multiple of d)
            sl = self.slice
            loss, new_state, grads = sl.run(
                self._bwd, sl.replicate(model.params),
                sl.replicate(model.state), sl.shard_batch(x_c),
                sl.shard_batch(y_c), sl.shard_batch(fm_c),
                sl.shard_batch(lm_c), sl.replicate(rng_j),
                sl.shard_batch(ew_c))
        else:
            loss, new_state, grads = self._bwd(
                model.params, model.state, x_c, y_c, fm_c, lm_c, rng_j,
                ew_c)
        arrays: Dict[str, np.ndarray] = {
            "n": np.asarray(n_j, np.int64),
            "loss": np.float32(loss) * w,  # graftlint: disable=host-sync
        }
        if self._flat_keys:
            gflat = np.concatenate([
                np.asarray(_flat(grads[k]), np.float32)  # graftlint: disable=host-sync
                for k in self._flat_keys]) * w
            if self.compress:
                res = self._residuals[j]
                packed, new_res = compression.encode_packed(
                    jnp.asarray(gflat), jnp.asarray(res), self.threshold)
                self._residuals[j] = np.asarray(new_res, np.float32)  # graftlint: disable=host-sync
                arrays["q"] = np.asarray(packed)  # graftlint: disable=host-sync
            else:
                arrays["g"] = gflat
        for key in self._dense_keys:
            for li, leaf in enumerate(jax.tree_util.tree_leaves(grads[key])):
                arrays[f"d{key}_{li}"] = (
                    np.asarray(leaf, np.float32) * w)  # graftlint: disable=host-sync
        for li, leaf in enumerate(jax.tree_util.tree_leaves(new_state)):
            a = np.asarray(leaf)  # graftlint: disable=host-sync
            if np.issubdtype(a.dtype, np.floating):
                a = (a.astype(np.float32) * w)
            arrays[f"s{li}"] = a
        return _pack_arrays(arrays)

    def _await_vshards(self, g: int, it: int, view: View, sync,
                       prefetch: Optional[_Prefetcher] = None,
                       ) -> List[Dict[str, np.ndarray]]:
        """Collect every vshard's payload for this step. A dead owner is
        unrecoverable mid-step (only it computed those gradients), so a
        lapsed lease drives a shrink and the survivors re-run the step."""
        v = self.vshards
        want = {j: f"grad/{g}/{it}/{j}" for j in range(v)}
        got: Dict[int, Dict[str, np.ndarray]] = {}
        deadline = time.monotonic() + self.rt.wait_timeout
        while want:
            if prefetch is not None:
                for j, arrays in prefetch.drain().items():
                    if j in want:
                        got[j] = arrays
                        del want[j]
            for j, key in list(want.items()):
                data = self.store.get(key)
                if data is not None:
                    got[j] = _unpack_arrays(data)
                    del want[j]
            if not want:
                break
            self.rt.check_for_change()
            dead = sorted({view.members[j % view.world] for j in want
                           if not self.rt.member_alive(
                               view.members[j % view.world])})
            if dead:
                self.rt.report_dead(dead, sync)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic step {it}: vshard payloads {sorted(want)} "
                    f"missing after {self.rt.wait_timeout:.0f}s")
            time.sleep(self.rt.poll)
        return [got[j] for j in range(v)]

    def _combine(self, payloads: List[Dict[str, np.ndarray]]):
        """Fixed-order (ascending vshard) sums of the weighted payloads:
        loss, flat gradient, dense per-leaf gradients, float state leaves.
        Order never depends on membership — the bit-exactness anchor."""
        loss = np.float32(0.0)
        gflat = np.zeros(self._total_n, np.float32)
        dense: Dict[str, np.ndarray] = {}
        state_f: Dict[str, np.ndarray] = {}
        state_i: Dict[str, np.ndarray] = {}
        packed = []
        for p in payloads:
            if int(p["n"]) == 0:
                continue
            loss = loss + p["loss"].astype(np.float32)
            if "q" in p:
                packed.append(p["q"])
            elif "g" in p:
                gflat += p["g"]
            for k, a in p.items():
                if k.startswith("d"):
                    dense[k] = dense[k] + a if k in dense else a.copy()
                elif k.startswith("s"):
                    if np.issubdtype(a.dtype, np.floating):
                        state_f[k] = (state_f[k] + a if k in state_f
                                      else a.copy())
                    elif k not in state_i:
                        state_i[k] = a
        if packed:
            summed = compression.decode_gathered(
                jnp.stack([jnp.asarray(q) for q in packed]),
                self._total_n, self.threshold, jnp.float32)
            gflat = np.asarray(summed, np.float32)  # graftlint: disable=host-sync
        # re-assemble the model state pytree from the summed leaves
        st_def = jax.tree_util.tree_structure(self.model.state)
        old_leaves = jax.tree_util.tree_leaves(self.model.state)
        new_leaves = []
        for li, old in enumerate(old_leaves):
            k = f"s{li}"
            if k in state_f:
                new_leaves.append(jnp.asarray(
                    state_f[k].astype(np.asarray(old).dtype)))  # graftlint: disable=host-sync
            elif k in state_i:
                new_leaves.append(jnp.asarray(state_i[k]))
            else:
                new_leaves.append(old)
        new_state = jax.tree_util.tree_unflatten(st_def, new_leaves)
        return loss, gflat, dense, new_state

    def _segment_update(self, gflat: np.ndarray, it: int, view: View):
        """Sharded optimizer update (arXiv 2004.13336): each worker updates
        its primary 1/W segment AND every segment it mirrors (R-way).
        Elementwise updater math means a segment's values are bitwise
        identical to the same elements of a full-vector update. Returns
        ``(new_segs, pnew_segs, my_pseg_arrays)`` — committed only after
        the whole step succeeds."""
        W = view.world
        r = view.rank_of(self.wid)
        held = self._held_ranks(r, W, self._view_racks(view))
        it_arr = jnp.asarray(it, jnp.int32)
        new_segs: Dict[int, Dict[int, np.ndarray]] = {}
        pnew: Dict[Tuple[int, int], np.ndarray] = {}
        off = 0
        for key in self._flat_keys:
            e = self._entries[key]
            m = self._m[key]
            n_pad = m * W
            g_pad = np.zeros(n_pad, np.float32)
            g_pad[:e.n] = gflat[off:off + e.n]
            off += e.n
            p_full = np.concatenate([
                np.ravel(np.asarray(l))  # graftlint: disable=host-sync
                for l in jax.tree_util.tree_leaves(self.model.params[key])])
            p_pad = np.zeros(n_pad, p_full.dtype)
            p_pad[:e.n] = p_full
            _, tdef = self._stat_template(e, m)
            new_segs[key] = {}
            for t in held:
                sl = slice(t * m, (t + 1) * m)
                g_seg = jnp.asarray(g_pad[sl]).astype(e.dtype)
                p_seg = jnp.asarray(p_pad[sl])
                o_tree = jax.tree_util.tree_unflatten(
                    tdef, [jnp.asarray(row)
                           for row in self._segs[key][t]])
                upd, o_new = e.updater.update(g_seg, o_tree, p_seg, it_arr)
                p_new = p_seg - upd
                leaves = jax.tree_util.tree_leaves(o_new)
                new_segs[key][t] = (
                    np.stack([np.asarray(l) for l in leaves])  # graftlint: disable=host-sync
                    if leaves else np.zeros((0, m), np.dtype(e.dtype)))
                pnew[(key, t)] = np.asarray(p_new)  # graftlint: disable=host-sync
        my_pseg = {f"k{key}": pnew[(key, r)] for key in self._flat_keys}
        return new_segs, pnew, my_pseg

    def _dense_update(self, dense_g: Dict[str, np.ndarray], it: int):
        """Replicated exact update for gn/constraint/mixed-dtype layers —
        the same math as ``model._update_params``, run identically on every
        worker."""
        it_arr = jnp.asarray(it, jnp.int32)
        new_params: Dict[int, Any] = {}
        new_opt: Dict[int, Any] = {}
        for key in self._dense_keys:
            e = self._entries[key]
            leaves = [jnp.asarray(dense_g[f"d{key}_{li}"])
                      for li in range(len(e.shapes))]
            g = jax.tree_util.tree_unflatten(
                e.treedef,
                [l.astype(pl.dtype) for l, pl in zip(
                    leaves,
                    jax.tree_util.tree_leaves(self.model.params[key]))])
            gn = getattr(e.cfg, "gradient_normalization", None)
            if gn:
                g = apply_gradient_normalization(
                    gn,
                    getattr(e.cfg, "gradient_normalization_threshold", 1.0),
                    g)
            upd, o_new = e.updater.update(
                g, self._dense_opt[key], self.model.params[key], it_arr)
            p_new = jax.tree_util.tree_map(
                lambda p, d: p - d, self.model.params[key], upd)
            if getattr(e.cfg, "constraints", None):
                from deeplearning4j_tpu.nn.constraints import apply_constraints

                p_new = apply_constraints(e.cfg, p_new)
            new_params[key] = p_new
            new_opt[key] = o_new
        return new_params, new_opt

    def _await_psegs(self, g: int, it: int, view: View, sync,
                     my_pseg: Dict[str, np.ndarray],
                     pnew: Dict[Tuple[int, int], np.ndarray],
                     prefetch: Optional[_Prefetcher] = None):
        """Collect every rank's updated param segment. A dead rank's segment
        is recoverable while ANY of its R-1 mirrors survives: the first
        surviving mirror (in placement order — every worker derives the
        same order) computed the identical update and serves it
        (``dl4j_elastic_mirror_serves_total``); only the loss of the owner
        AND all its mirrors forces the shrink-and-rerun path."""
        W = view.world
        r = view.rank_of(self.wid)
        racks = self._view_racks(view)
        got: Dict[int, Dict[str, np.ndarray]] = {r: my_pseg}
        want = {t: f"pseg/{g}/{it}/{t}" for t in range(W) if t != r}
        deadline = time.monotonic() + self.rt.wait_timeout
        while want:
            if prefetch is not None:
                for t, arrays in prefetch.drain().items():
                    if t in want:
                        got[t] = arrays
                        del want[t]
            for t, key in list(want.items()):
                data = self.store.get(key)
                if data is not None:
                    got[t] = _unpack_arrays(data)
                    del want[t]
            if not want:
                break
            self.rt.check_for_change()
            unrecoverable = []
            for t in list(want):
                if self.rt.member_alive(view.members[t]):
                    continue
                mirrors = mirror_ranks(t, W, self.replication, racks)
                alive = [s for s in mirrors if s == r
                         or self.rt.member_alive(view.members[s])]
                if not alive:
                    unrecoverable.append(view.members[t])
                elif alive[0] == r:
                    served = {f"k{key}": pnew[(key, t)]
                              for key in self._flat_keys}
                    self.store.set(f"pseg/{g}/{it}/{t}",
                                   _pack_arrays(served))
                    got[t] = served
                    del want[t]
                    obs.counter(
                        "dl4j_elastic_mirror_serves_total",
                        "Dead ranks' param segments served from a "
                        "surviving mirror").inc()
                    obs.event("elastic_mirror_serve", rank=t, by=self.wid,
                              iteration=it, gen=g)
                # else: an earlier surviving mirror serves; keep waiting on
                # the pseg key it will publish
            if unrecoverable:
                self.rt.report_dead(sorted(set(unrecoverable)), sync)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic step {it}: param segments {sorted(want)} "
                    f"missing after {self.rt.wait_timeout:.0f}s")
            time.sleep(self.rt.poll)
        return got

    def _assemble_params(self, got: Dict[int, Dict[str, np.ndarray]],
                         dense_params: Dict[int, Any], view: View):
        W = view.world
        params = []
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                params.append(self.model.params[key])
            elif e.mode == "dense":
                params.append(dense_params[key])
            else:
                m = self._m[key]
                flat = np.concatenate(
                    [got[t][f"k{key}"] for t in range(W)])[:e.n]
                params.append(_unflat(jnp.asarray(flat), e))
        self.model.params = tuple(params)

    def _run_step(self, xb, yb):
        view = self.rt.view
        it = int(self.model.iteration)
        sync = (self.epoch, self.step_in_epoch, it)
        r = view.rank_of(self.wid)
        W = view.world
        # the work-wall window opens BEFORE the chaos hooks: an injected
        # slow_iter stall is exactly the straggler signal the skew
        # detector exists to catch
        t_start = time.monotonic()
        self._chaos_hooks(it, r)
        self.rt.poll_boundary(sync)
        g = view.gen
        mine = set(self._my_vshards())
        fetchers: List[_Prefetcher] = []
        stall0 = self.stall_s
        try:
            with obs.span("elastic.step"):
                if self.async_exchange and len(mine) < self.vshards:
                    # overlap fetching the peers' vshard payloads with
                    # computing my own backward passes
                    fetchers.append(_Prefetcher(
                        self.store,
                        {j: f"grad/{g}/{it}/{j}"
                         for j in range(self.vshards) if j not in mine},
                        self.rt.poll))
                for j in self._my_vshards():
                    self.store.set(f"grad/{g}/{it}/{j}",
                                   self._vshard_payload(j, xb, yb, it))
                t0 = time.monotonic()
                payloads = self._await_vshards(
                    g, it, view, sync,
                    prefetch=fetchers[0] if fetchers else None)
                self.stall_s += time.monotonic() - t0
                loss, gflat, dense_g, new_state = self._combine(payloads)
                new_segs, pnew, my_pseg = self._segment_update(
                    gflat, it, view)
                self.store.set(f"pseg/{g}/{it}/{r}", _pack_arrays(my_pseg))
                pf = None
                if self.async_exchange and W > 1:
                    # overlap fetching the peers' param segments with the
                    # dense (replicated) update below
                    pf = _Prefetcher(
                        self.store,
                        {t: f"pseg/{g}/{it}/{t}"
                         for t in range(W) if t != r},
                        self.rt.poll)
                    fetchers.append(pf)
                dense_params, dense_opt = self._dense_update(dense_g, it)
                t0 = time.monotonic()
                got = self._await_psegs(g, it, view, sync, my_pseg, pnew,
                                        prefetch=pf)
                self.stall_s += time.monotonic() - t0
                # commit: nothing above mutated trainer/model state, so a
                # membership change mid-step leaves us at the exact boundary
                # the re-formed group re-runs from
                self._assemble_params(got, dense_params, view)
                self._segs = new_segs
                self._dense_opt.update(dense_opt)
                self.model.state = new_state
                self.model.iteration = it + 1
                self.losses.append(float(loss))
        finally:
            for f in fetchers:
                f.stop()
        stall = self.stall_s - stall0
        obs.histogram("dl4j_elastic_boundary_stall_seconds",
                      "Per-step time blocked waiting on DCN payloads "
                      "(vshards + param segments)").observe(stall)
        # straggler detection input: the WORK wall (total minus time spent
        # blocked on peers' payloads). Total walls equalize across ranks —
        # every waiter stalls on the straggler — so only the stall-free
        # component attributes the skew to the rank that caused it.
        work_s = max(time.monotonic() - t_start - stall, 0.0)
        self._publish_stepwall(g, it, r, W, work_s)
        if r == 0 and it >= 2:
            self.store.prune(f"grad/{g}/{it - 2}")
            self.store.prune(f"pseg/{g}/{it - 2}")
            self.store.prune(f"obs/stepwall/{g}/{it - 2}")
        return float(loss)

    def _publish_stepwall(self, g: int, it: int, r: int, W: int,
                          work_s: float) -> None:
        """Publish this rank's per-step work wall and (on rank 0) evaluate
        the skew detector over iteration ``it - 1``, whose walls every
        rank is guaranteed to have published — the pseg exchange of step
        ``it`` cannot complete before every rank finished step ``it - 1``
        — so the read loop below never waits."""
        try:
            self.store.set(fleet.stepwall_key(g, it, r),
                           json.dumps({"wall_s": work_s}).encode())
            if r != 0 or it < 1 or W < 2:
                return
            walls: Dict[int, float] = {}
            for t in range(W):
                raw = self.store.get(fleet.stepwall_key(g, it - 1, t))
                if raw is None:
                    return  # gen reformed mid-window: skip this boundary
                walls[t] = float(json.loads(raw.decode())["wall_s"])
            self._stragglers.update(self._straggler.observe(it - 1, walls))
        except Exception:
            pass  # observability must never fail the step

    # -- distributed checkpoints ---------------------------------------------
    def _maybe_checkpoint(self):
        if (not self.ckpt_dir or self.ckpt_every <= 0
                or self.model.iteration % self.ckpt_every != 0):
            return
        view = self.rt.view
        r = view.rank_of(self.wid)
        tag = f"{int(self.model.iteration):08d}"
        os.makedirs(self.ckpt_dir, exist_ok=True)
        arrays = {}
        for key in self._flat_keys:
            for t, seg in self._segs[key].items():
                arrays[f"k{key}_t{t}"] = seg
        for j, res in self._residuals.items():
            arrays[f"res{j}"] = res
        shard_name = f"shard_{tag}_r{r}.npz"
        shard_path = os.path.join(self.ckpt_dir, shard_name)
        data = _pack_arrays(arrays)
        resilience.write_bytes_durable(shard_path, data)
        self.store.set_json(f"ckmeta/{view.gen}/{tag}/{r}", {
            "file": shard_name, "crc": resilience.crc32_file(shard_path),
            "size": os.path.getsize(shard_path), "rank": r, "wid": self.wid})
        from deeplearning4j_tpu.nn import aot

        aot.save_distributed_bundle(
            self.model, os.path.join(self.ckpt_dir, f"ckpt_{tag}"), r)
        if r != 0:
            return
        # rank 0 writes the replicated arrays + the CRC'd manifest (the
        # commit point: a manifest only lands after every shard checks in)
        model = self.model
        pa: Dict[str, np.ndarray] = {}
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                continue
            for li, leaf in enumerate(
                    jax.tree_util.tree_leaves(model.params[key])):
                pa[f"p{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        for key in self._dense_keys:
            for li, leaf in enumerate(
                    jax.tree_util.tree_leaves(self._dense_opt[key])):
                pa[f"o{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        for li, leaf in enumerate(jax.tree_util.tree_leaves(model.state)):
            pa[f"st{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        rng = np.asarray(self._base_rng)  # graftlint: disable=host-sync
        pa["meta"] = _json_to_array({
            "iteration": int(model.iteration), "epoch": self.epoch,
            "step": self.step_in_epoch, "base_rng": rng.tolist(),
            "base_rng_dtype": str(rng.dtype),
            "losses": [float(v) for v in self.losses],
            "vshards": int(self.vshards)})
        params_name = f"ckpt_{tag}_params.npz"
        params_path = os.path.join(self.ckpt_dir, params_name)
        resilience.write_bytes_durable(params_path, _pack_arrays(pa))
        metas: Dict[int, dict] = {}
        deadline = time.monotonic() + max(2 * self.rt.ttl, 5.0)
        while len(metas) < view.world:
            for t in range(view.world):
                if t in metas:
                    continue
                d = self.store.get_json(f"ckmeta/{view.gen}/{tag}/{t}")
                if d is not None:
                    metas[t] = d
            if len(metas) == view.world:
                break
            if time.monotonic() > deadline:
                obs.event("elastic_checkpoint_aborted", tag=tag,
                          have=sorted(metas), world=view.world)
                return
            time.sleep(self.rt.poll)
        manifest = {
            "format": 1, "tag": tag, "iteration": int(model.iteration),
            "epoch": self.epoch, "step": self.step_in_epoch,
            "world": view.world, "members": list(view.members),
            "vshards": int(self.vshards),
            "params": {"file": params_name,
                       "crc": resilience.crc32_file(params_path),
                       "size": os.path.getsize(params_path)},
            "shards": {str(t): metas[t] for t in range(view.world)},
        }
        resilience.write_json_durable(
            os.path.join(self.ckpt_dir, f"manifest_{tag}.json"), manifest)
        obs.counter("dl4j_elastic_checkpoints_total",
                    "Distributed checkpoints committed (manifest written)"
                    ).inc()
        obs.event("elastic_checkpoint", tag=tag, world=view.world,
                  iteration=int(model.iteration))

    # -- finalization --------------------------------------------------------
    def _final_gather(self):
        """Assemble the full structured optimizer state back onto the model
        (the fit-exit contract: outside a fit the model stays
        serializable/usable, like ``DataParallelStep.finish``)."""
        view = self.rt.view
        g = view.gen
        arrays = {}
        for key in self._flat_keys:
            for t, seg in self._segs[key].items():
                arrays[f"k{key}_t{t}"] = seg
        self.store.set(f"fin/{g}/{self.wid}", _pack_arrays(arrays))
        sync = (self.epoch, self.step_in_epoch, int(self.model.iteration))
        want = {wid: f"fin/{g}/{wid}" for wid in view.members
                if wid != self.wid}
        got = {self.wid: arrays}
        deadline = time.monotonic() + self.rt.wait_timeout
        while want:
            for wid, key in list(want.items()):
                data = self.store.get(key)
                if data is not None:
                    got[wid] = _unpack_arrays(data)
                    del want[wid]
            if not want:
                break
            self.rt.check_for_change()
            dead = [wid for wid in want if not self.rt.member_alive(wid)]
            if dead:
                self.rt.report_dead(dead, sync)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic finalize gen {g}: waiting on {sorted(want)}")
            time.sleep(self.rt.poll)
        W = view.world
        new_opt = []
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                new_opt.append(self.model.opt_state[key])
            elif e.mode == "dense":
                new_opt.append(self._dense_opt[key])
            else:
                racks = self._view_racks(view)
                full = self._assemble_full_stats(
                    e, W,
                    lambda t, k=key: next(
                        (got[view.members[s]][f"k{k}_t{t}"]
                         for s in [t] + mirror_ranks(
                             t, W, self.replication, racks)
                         if view.members[s] in got
                         and f"k{k}_t{t}" in got[view.members[s]]), None))
                if full is None:
                    raise RuntimeError(
                        f"elastic finalize: layer {key} segments missing")
                new_opt.append(self._stats_structured_from_full(e, full))
        self.model.opt_state = tuple(new_opt)

    def _publish_done(self):
        """Rank 0's terminal blob: the fully-gathered final model state, so
        a worker partitioned through the END of the job still lands on the
        uninterrupted run's parameters instead of hanging on readmission."""
        model = self.model
        full: Dict[str, np.ndarray] = {}
        for key in self._order:
            if key not in self._entries:
                continue
            for li, leaf in enumerate(
                    jax.tree_util.tree_leaves(model.params[key])):
                full[f"p{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
            for li, leaf in enumerate(
                    jax.tree_util.tree_leaves(model.opt_state[key])):
                full[f"o{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        for li, leaf in enumerate(jax.tree_util.tree_leaves(model.state)):
            full[f"st{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
        rng = np.asarray(self._base_rng)  # graftlint: disable=host-sync
        full["meta"] = _json_to_array({
            "iteration": int(model.iteration), "epoch": self.epoch,
            "step": self.step_in_epoch, "base_rng": rng.tolist(),
            "base_rng_dtype": str(rng.dtype),
            "losses": [float(v) for v in self.losses]})
        self.store.set("done", _pack_arrays(full))

    def _adopt_done(self):
        model = self.model
        full = _unpack_arrays(self.store.get("done"))
        meta = _array_to_json(full["meta"])
        params, opt = [], []
        for key in self._order:
            e = self._entries.get(key)
            if e is None:
                params.append(model.params[key])
                opt.append(model.opt_state[key])
                continue
            params.append(jax.tree_util.tree_unflatten(
                e.treedef, [jnp.asarray(full[f"p{key}_{li}"])
                            for li in range(len(e.shapes))]))
            n_o = len(jax.tree_util.tree_leaves(model.opt_state[key]))
            opt.append(jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(model.opt_state[key]),
                [jnp.asarray(full[f"o{key}_{li}"]) for li in range(n_o)]))
        model.params = tuple(params)
        model.opt_state = tuple(opt)
        st_def = jax.tree_util.tree_structure(model.state)
        n_st = len(jax.tree_util.tree_leaves(model.state))
        model.state = jax.tree_util.tree_unflatten(
            st_def, [jnp.asarray(full[f"st{li}"]) for li in range(n_st)])
        model.iteration = int(meta["iteration"])
        self.epoch, self.step_in_epoch = int(meta["epoch"]), int(meta["step"])
        self.losses = [float(v) for v in meta.get("losses", [])]
        obs.event("elastic_done_adopted", wid=self.wid,
                  iteration=model.iteration)

    # -- fit -----------------------------------------------------------------
    def fit(self, x, y, *, epochs: int, batch_size: int) -> dict:
        """Train for ``epochs`` over ``(x, y)`` elastically; returns a result
        dict (loss curve, final membership). Deterministic batch order; the
        global batch of step ``s`` is rows ``[s*bs, (s+1)*bs)``."""
        x = np.asarray(x)
        y = np.asarray(y)
        bs = int(batch_size)
        self._steps_per_epoch = max(-(-len(x) // bs), 1)
        view = self.rt.bootstrap(self.world)
        epochs = int(epochs)
        try:
            self._reform_initial(view)
            self._vshard_rows = self._rows_per_vshard(bs)
            while self.epoch < epochs:
                s = self.step_in_epoch
                lo = s * bs
                xb, yb = x[lo:lo + bs], y[lo:lo + bs]
                try:
                    self._run_step(xb, yb)
                except MembershipChanged as mc:
                    self._reform(mc.view)
                    self._vshard_rows = self._rows_per_vshard(bs)
                    continue
                self.step_in_epoch += 1
                if self.step_in_epoch >= self._steps_per_epoch:
                    self.step_in_epoch = 0
                    self.epoch += 1
                self._maybe_checkpoint()
                self._maybe_publish_snapshot()
            while True:
                try:
                    self._final_gather()
                    break
                except MembershipChanged as mc:
                    self._reform(mc.view)
            if self.rt.view.rank_of(self.wid) == 0:
                self._publish_done()
        except _JobDone:
            pass
        self._maybe_publish_snapshot(force=True)
        view = self.rt.view
        return {
            "wid": self.wid,
            "rank": view.rank_of(self.wid),
            "world": view.world,
            "gen": view.gen,
            "iteration": int(self.model.iteration),
            "losses": [float(v) for v in self.losses],
            "final_loss": (float(self.losses[-1]) if self.losses
                           else float("nan")),
            "stall_s": float(self.stall_s),
            "replication": int(self.replication),
            "rack": self.rt.rack,
            "store_backend": getattr(self.store, "backend", "file"),
            "async_exchange": bool(self.async_exchange),
            "stragglers": sorted(self._stragglers),
        }

    def _maybe_publish_snapshot(self, force: bool = False) -> None:
        """Publish this worker's metrics snapshot for the fleet collector —
        report-time only, throttled to ~1/s so the store sees one small
        write per worker per second, not per step."""
        now = time.monotonic()
        if not force and now - self._last_publish < 1.0:
            return
        self._last_publish = now
        try:
            fleet.publish_snapshot(self.store, self.wid)
        except Exception:
            pass  # observability must never fail training

    def _rows_per_vshard(self, bs: int) -> int:
        """Padded rows per vshard micro-batch; rounded up to the slice's
        data-axis size so the in-slice batch sharding divides evenly."""
        rows = -(-bs // self.vshards)
        return self.slice.round_rows(rows) if self.slice else rows

    def _reform_initial(self, view: View):
        """Initial form after bootstrap — same machinery as any reform, via
        a synthetic MembershipChanged so churn-during-handoff retries work
        from the first generation on."""
        self._reform(view)

    def close(self):
        self.rt.leave()


# ---------------------------------------------------------------------------
# CLI: worker + local launcher (tests/test_elastic.py, tools/elastic_smoke.sh)
# ---------------------------------------------------------------------------


def _build_model(args):
    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration,
        MultiLayerNetwork,
    )

    hidden = [int(h) for h in str(args.hidden).split(",") if h]
    layers = tuple(Dense(n_out=h, activation="tanh") for h in hidden) + (
        OutputLayer(n_out=int(args.classes), activation="softmax"),)
    conf = MultiLayerConfiguration(
        layers=layers,
        input_type=InputType.feed_forward(int(args.features)),
        updater={"type": "adam", "lr": float(args.lr)},
        seed=int(args.seed),
    )
    return MultiLayerNetwork(conf).init()


def _make_data(args):
    rs = np.random.RandomState(int(args.seed))
    n, f, c = int(args.n), int(args.features), int(args.classes)
    x = rs.randn(n, f).astype(np.float32)
    y = np.eye(c, dtype=np.float32)[rs.randint(0, c, n)]
    return x, y


def _cmd_worker(args) -> int:
    os.makedirs(args.outdir, exist_ok=True)
    obs.configure_event_log(
        os.path.join(args.outdir, f"events_{args.id}.jsonl"))
    model = _build_model(args)
    trainer = ElasticTrainer(
        model, args.store, args.id, world=args.world,
        vshards=args.vshards, compress=args.compress,
        threshold=args.threshold,
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
        ttl=args.ttl, poll=args.poll,
        replication=args.replication or None,
        rack=args.rack if args.rack else None,
        slice_spec=args.mesh or None,
        async_exchange=None if args.async_exchange < 0
        else bool(args.async_exchange))
    x, y = _make_data(args)
    try:
        result = trainer.fit(x, y, epochs=args.epochs,
                             batch_size=args.batch)
    finally:
        trainer.close()
        # span dump for the merged fleet timeline (trace_export merge):
        # one file per worker, each carrying its own wall<->perf anchor
        # and rank/incarnation process context
        obs.save_spans(os.path.join(args.outdir, f"spans_{args.id}.json"))
    params = {}
    for key, p in enumerate(model.params):
        for li, leaf in enumerate(jax.tree_util.tree_leaves(p)):
            params[f"p{key}_{li}"] = np.asarray(leaf)  # graftlint: disable=host-sync
    # Publish atomically: the harness (and a relaunch supervisor) may read
    # these while a preemption kills this process mid-write — a torn
    # params_N.npz/result_N.json would poison the post-mortem checks.
    params_path = os.path.join(args.outdir, f"params_{args.id}.npz")
    tmp = params_path + f".{os.getpid()}.tmp.npz"  # np.savez appends .npz
    np.savez(tmp, **params)
    os.replace(tmp, params_path)
    result_path = os.path.join(args.outdir, f"result_{args.id}.json")
    tmp = result_path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(result, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, result_path)
    print(json.dumps(result))
    return 0


def _cmd_launch(args) -> int:
    """Local supervisor: spawn N workers, optionally relaunch killed ones
    (the preempted-worker-rejoins path). Relaunched processes get the chaos
    env stripped — the one-shot fault already fired in the dead process and
    must not re-fire at the (now higher) resume iteration."""
    procs: Dict[str, subprocess.Popen] = {}
    relaunches = int(args.relaunch)
    allowed_failures = int(args.allow_failures)
    failures: List[str] = []

    racks = [r.strip() for r in args.racks.split(",")] if args.racks else []

    def spawn(wid: str, chaos: bool) -> subprocess.Popen:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if args.mesh and args.slice_devices:
            # Must land in the child's env before jax imports: device count
            # is fixed at backend init.
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count="
                                f"{args.slice_devices}").strip()
        if not chaos:
            env.pop("DL4J_TPU_CHAOS", None)
        cmd = [sys.executable, "-m", "deeplearning4j_tpu.train.elastic",
               "worker", "--store", args.store, "--outdir", args.outdir,
               "--id", wid, "--world", str(args.world),
               "--epochs", str(args.epochs), "--batch", str(args.batch),
               "--n", str(args.n), "--features", str(args.features),
               "--classes", str(args.classes), "--hidden", str(args.hidden),
               "--lr", str(args.lr), "--seed", str(args.seed),
               "--ttl", str(args.ttl), "--poll", str(args.poll),
               "--threshold", str(args.threshold)]
        if racks:
            wi = int(wid[1:])
            cmd += ["--rack", racks[wi % len(racks)]]
        if args.replication:
            cmd += ["--replication", str(args.replication)]
        if args.mesh:
            cmd += ["--mesh", args.mesh]
        if args.async_exchange >= 0:
            cmd += ["--async-exchange", str(args.async_exchange)]
        if args.vshards:
            cmd += ["--vshards", str(args.vshards)]
        if args.compress:
            cmd += ["--compress"]
        if args.ckpt_dir:
            cmd += ["--ckpt-dir", args.ckpt_dir,
                    "--ckpt-every", str(args.ckpt_every)]
        return subprocess.Popen(cmd, env=env)

    wids = [f"w{i}" for i in range(int(args.workers))]
    for wid in wids:
        procs[wid] = spawn(wid, chaos=True)
    if args.fleet_port >= 0:
        # fleet metrics federation: serve the merged exposition of every
        # worker's published snapshot while the run is live
        from deeplearning4j_tpu.obs import fleet as fleet_mod

        _, _, bound = fleet_mod.serve_collector(open_store(args.store),
                                                port=args.fleet_port)
        print(json.dumps({"fleet_port": bound}), flush=True)
    deadline = time.monotonic() + float(args.timeout)
    done: Dict[str, int] = {}
    while len(done) < len(wids):
        for wid, p in list(procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            del procs[wid]
            if rc == 0:
                done[wid] = 0
            elif relaunches > 0:
                relaunches -= 1
                print(f"[launch] worker {wid} exited rc={rc}; relaunching",
                      flush=True)
                procs[wid] = spawn(wid, chaos=False)
            elif len(failures) < allowed_failures:
                failures.append(wid)
                done[wid] = rc
                print(f"[launch] worker {wid} exited rc={rc} "
                      "(allowed failure)", flush=True)
            else:
                for q in procs.values():
                    q.kill()
                print(f"[launch] worker {wid} exited rc={rc}; aborting",
                      flush=True)
                return 1
        if time.monotonic() > deadline:
            for q in procs.values():
                q.kill()
            print("[launch] timeout", flush=True)
            return 1
        time.sleep(0.05)
    survivors = [w for w in wids if done[w] == 0]
    print(json.dumps({"survivors": survivors, "failures": failures}))
    return 0 if survivors else 1


def _parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.train.elastic",
        description="Elastic data-parallel training: worker process and "
                    "local launcher for the synthetic workload")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--store", required=True,
                       help="shared rendezvous/exchange store: a directory "
                            "(or file:DIR) for FileStore, tcp://host:port "
                            "for the network store")
        p.add_argument("--outdir", required=True)
        p.add_argument("--world", type=int, default=2)
        p.add_argument("--epochs", type=int, default=3)
        p.add_argument("--batch", type=int, default=16)
        p.add_argument("--n", type=int, default=48)
        p.add_argument("--features", type=int, default=10)
        p.add_argument("--classes", type=int, default=4)
        p.add_argument("--hidden", default="16,8")
        p.add_argument("--lr", type=float, default=5e-3)
        p.add_argument("--seed", type=int, default=7)
        p.add_argument("--vshards", type=int, default=0)
        p.add_argument("--compress", action="store_true")
        p.add_argument("--threshold", type=float, default=1e-3)
        p.add_argument("--ckpt-dir", dest="ckpt_dir", default=None)
        p.add_argument("--ckpt-every", dest="ckpt_every", type=int,
                       default=0)
        p.add_argument("--ttl", type=float, default=2.0)
        p.add_argument("--poll", type=float, default=0.02)
        p.add_argument("--rack", default="",
                       help="failure-domain label for this worker "
                            "(mirror placement avoids the owner's rack)")
        p.add_argument("--replication", type=int, default=0,
                       help="R-way mirror replication factor "
                            "(0 = env/default)")
        p.add_argument("--mesh", default="",
                       help="per-member slice spec 'd[,t[,s]]' — run each "
                            "member as a mesh_step slice of that shape")
        p.add_argument("--async-exchange", dest="async_exchange",
                       type=int, default=-1,
                       help="1/0 force async DCN payload prefetch on/off "
                            "(-1 = env/default)")

    w = sub.add_parser("worker", help="run one elastic worker")
    common(w)
    w.add_argument("--id", required=True)
    w.set_defaults(fn=_cmd_worker)

    l = sub.add_parser("launch", help="supervise N local workers")
    common(l)
    l.add_argument("--workers", type=int, default=2)
    l.add_argument("--racks", default="",
                   help="comma-separated rack label per worker "
                        "(w0,w1,... ; cycled if shorter than --workers)")
    l.add_argument("--slice-devices", dest="slice_devices", type=int,
                   default=0,
                   help="virtual CPU device count per worker when --mesh "
                        "is set (injects xla_force_host_platform_"
                        "device_count)")
    l.add_argument("--relaunch", type=int, default=0,
                   help="relaunch budget for killed workers (rejoin path)")
    l.add_argument("--fleet-port", dest="fleet_port", type=int, default=-1,
                   help="serve the fleet metrics collector "
                        "(/fleet/metrics) on this port while workers run "
                        "(0 = OS-assigned; -1 = off)")
    l.add_argument("--allow-failures", dest="allow_failures", type=int,
                   default=0)
    l.add_argument("--timeout", type=float, default=300.0)
    l.set_defaults(fn=_cmd_launch)
    return ap


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if getattr(args, "vshards", 0) == 0:
        args.vshards = None
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
