"""Updaters (optimizers), learning-rate schedules, gradient normalization.

Capability parity with ND4J's ``GradientUpdater`` family consumed by the
reference's updater stack (nn/updater/BaseMultiLayerUpdater.java,
nn/updater/UpdaterBlock.java:142): SGD, Adam, AdaMax, Nadam, AMSGrad,
Nesterovs, AdaGrad, AdaDelta, RMSProp, NoOp; LR decay policies (exponential,
inverse, poly, sigmoid, step, explicit map schedule); and the
``GradientNormalization`` modes applied in ``preApply``
(BaseMultiLayerUpdater.java:322).

Design: an updater is a pure pytree transform — ``init(params) -> state`` and
``update(grads, state, params, step) -> (updates, new_state)`` — applied as
``params - updates``. No flattened views, no UpdaterBlocks: state lives in
the same pytree structure as the params and shards with them under pjit.
Per-layer updater overrides (a DL4J feature: each layer config may carry its
own updater) are handled by the model, which builds one transform per layer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Spec normalization
# ---------------------------------------------------------------------------

_DEFAULTS: Dict[str, Dict[str, float]] = {
    "sgd": {"lr": 0.1},
    "adam": {"lr": 1e-3, "beta1": 0.9, "beta2": 0.999, "eps": 1e-8},
    "adamax": {"lr": 2e-3, "beta1": 0.9, "beta2": 0.999, "eps": 1e-8},
    "nadam": {"lr": 1e-3, "beta1": 0.9, "beta2": 0.999, "eps": 1e-8},
    "amsgrad": {"lr": 1e-3, "beta1": 0.9, "beta2": 0.999, "eps": 1e-8},
    "nesterovs": {"lr": 0.1, "momentum": 0.9},
    "adagrad": {"lr": 0.1, "eps": 1e-6},
    "adadelta": {"rho": 0.95, "eps": 1e-6},
    "rmsprop": {"lr": 1e-3, "decay": 0.95, "eps": 1e-8},
    "noop": {},
}

_ALIASES = {"momentum": "nesterovs", "nesterov": "nesterovs", "none": "noop"}


def normalize_updater(spec: Any) -> dict:
    """Accept ``"adam"``, ``{"type": "adam", "lr": 1e-3, ...}``, or an already
    normalized dict; return a full dict with defaults filled in."""
    if spec is None:
        spec = "sgd"
    if isinstance(spec, str):
        spec = {"type": spec}
    t = str(spec.get("type", "sgd")).lower()
    t = _ALIASES.get(t, t)
    if t not in _DEFAULTS:
        raise ValueError(f"Unknown updater '{t}'. Known: {sorted(_DEFAULTS)}")
    out = {"type": t}
    out.update(_DEFAULTS[t])
    for k, v in spec.items():
        if k != "type":
            out[k] = v
    return out


def scale_lr(spec: Any, scale: float) -> dict:
    """Normalized updater spec with its base LR multiplied by ``scale`` —
    the divergence-guard rollback backoff (train/resilience.py). A no-op at
    scale 1.0 and for LR-free updaters (adadelta, noop)."""
    cfg = normalize_updater(spec)
    if scale != 1.0 and "lr" in cfg:
        cfg = dict(cfg, lr=cfg["lr"] * float(scale))
    return cfg


# ---------------------------------------------------------------------------
# Learning-rate schedules (reference: LearningRatePolicy + ISchedule impls)
# ---------------------------------------------------------------------------


def schedule_value(spec: Any, base_lr, step) -> jax.Array:
    """Evaluate an LR schedule at ``step`` (an int or traced scalar).

    ``spec`` may be None (constant), or a dict:
      {"policy": "exponential", "decay_rate": g}          lr * g^step
      {"policy": "inverse", "gamma": g, "power": p}       lr / (1+g*step)^p
      {"policy": "poly", "power": p, "max_iter": n}       lr * (1-step/n)^p
      {"policy": "sigmoid", "gamma": g, "step_size": s}   lr / (1+exp(-g*(step-s)))
      {"policy": "step", "decay_rate": g, "step_size": s} lr * g^floor(step/s)
      {"policy": "map", "schedule": {"0": lr0, "1000": lr1}}  piecewise-constant
      {"policy": "warmup_cosine", "warmup": w, "max_iter": n, "min_lr": m}
    Step-indexed (the reference supports iteration or epoch schedules; the
    model passes whichever counter the config selects).
    """
    step = jnp.asarray(step, jnp.float32)
    base = jnp.asarray(base_lr, jnp.float32)
    if spec is None:
        return base
    policy = str(spec.get("policy", "constant")).lower()
    if policy == "constant":
        return base
    if policy == "exponential":
        return base * spec.get("decay_rate", 0.99) ** step
    if policy == "inverse":
        g, p = spec.get("gamma", 1e-3), spec.get("power", 1.0)
        return base / (1.0 + g * step) ** p
    if policy == "poly":
        p, n = spec.get("power", 1.0), float(spec.get("max_iter", 10000))
        return base * jnp.clip(1.0 - step / n, 0.0, 1.0) ** p
    if policy == "sigmoid":
        g, s = spec.get("gamma", 0.01), float(spec.get("step_size", 0))
        return base / (1.0 + jnp.exp(-g * (step - s)))
    if policy == "step":
        g, s = spec.get("decay_rate", 0.1), float(spec.get("step_size", 1000))
        return base * g ** jnp.floor(step / s)
    if policy == "map":
        sched = {int(k): float(v) for k, v in spec["schedule"].items()}
        lr = base
        for boundary in sorted(sched):
            lr = jnp.where(step >= boundary, sched[boundary], lr)
        return lr
    if policy == "warmup_cosine":
        w = float(spec.get("warmup", 0))
        n = float(spec.get("max_iter", 10000))
        m = float(spec.get("min_lr", 0.0))
        warm = base * step / jnp.maximum(w, 1.0)
        t = jnp.clip((step - w) / jnp.maximum(n - w, 1.0), 0.0, 1.0)
        cos = m + 0.5 * (base - m) * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(step < w, warm, cos)
    raise ValueError(f"Unknown LR policy '{policy}'")


# ---------------------------------------------------------------------------
# Updater transforms
# ---------------------------------------------------------------------------


class Updater(NamedTuple):
    """A pure optimizer transform over a params pytree."""

    init: Callable[[Any], Any]
    update: Callable[..., Tuple[Any, Any]]  # (grads, state, params, step) -> (updates, new_state)
    spec: dict


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _zeros_like_tree(params):
    return _tmap(jnp.zeros_like, params)


_LOW_PRECISION = (jnp.bfloat16, jnp.float16)


def _mixed_precision(raw: Updater) -> Updater:
    """Mixed-precision wrapper: accumulators live in float32, the update is
    computed in float32, and only the returned delta is cast to the param
    dtype — so bf16 training (the TPU default) keeps f32 optimizer statistics
    and params stay bf16 across steps (no silent f32 promotion)."""

    def up32(x):
        return x.astype(jnp.float32) if x.dtype in _LOW_PRECISION else x

    def init(params):
        return raw.init(_tmap(up32, params))

    def update(g, s, params, step):
        upd, ns = raw.update(_tmap(up32, g), s, _tmap(up32, params), step)
        upd = _tmap(lambda u, p: u.astype(p.dtype), upd, params)
        return upd, ns

    return Updater(init, update, raw.spec)


def make_updater(spec: Any) -> Updater:
    """Build an :class:`Updater` from a spec.

    The returned ``update`` computes the quantity SUBTRACTED from params
    (DL4J convention: ``GradientUpdater.applyUpdater`` rewrites the gradient
    into the update in-place; here it is pure).
    """
    return _mixed_precision(_make_raw_updater(spec))


def _make_raw_updater(spec: Any) -> Updater:
    cfg = normalize_updater(spec)
    t = cfg["type"]
    sched = cfg.get("schedule")

    def lr_at(step):
        return schedule_value(sched, cfg.get("lr", 0.0), step)

    if t == "noop":
        return Updater(
            init=lambda params: (),
            update=lambda g, s, params, step: (_tmap(jnp.zeros_like, g), s),
            spec=cfg,
        )

    if t == "sgd":
        return Updater(
            init=lambda params: (),
            update=lambda g, s, params, step: (_tmap(lambda gi: lr_at(step) * gi, g), s),
            spec=cfg,
        )

    if t == "nesterovs":
        mu = cfg["momentum"]
        mu_sched = cfg.get("momentum_schedule")

        def init(params):
            return {"v": _zeros_like_tree(params)}

        def update(g, s, params, step):
            lr = lr_at(step)
            m = schedule_value(mu_sched, mu, step) if mu_sched else mu
            # DL4J NesterovsUpdater: v' = mu*v - lr*g ; update = -(mu*v' - lr*g)
            v_new = _tmap(lambda vi, gi: m * vi - lr * gi, s["v"], g)
            upd = _tmap(lambda vn, gi: -(m * vn - lr * gi), v_new, g)
            return upd, {"v": v_new}

        return Updater(init, update, cfg)

    if t == "adagrad":
        eps = cfg["eps"]

        def init(params):
            return {"h": _zeros_like_tree(params)}

        def update(g, s, params, step):
            lr = lr_at(step)
            h_new = _tmap(lambda hi, gi: hi + gi * gi, s["h"], g)
            upd = _tmap(lambda hi, gi: lr * gi / (jnp.sqrt(hi) + eps), h_new, g)
            return upd, {"h": h_new}

        return Updater(init, update, cfg)

    if t == "rmsprop":
        d, eps = cfg["decay"], cfg["eps"]

        def init(params):
            return {"c": _zeros_like_tree(params)}

        def update(g, s, params, step):
            lr = lr_at(step)
            c_new = _tmap(lambda ci, gi: d * ci + (1 - d) * gi * gi, s["c"], g)
            upd = _tmap(lambda ci, gi: lr * gi / (jnp.sqrt(ci + eps)), c_new, g)
            return upd, {"c": c_new}

        return Updater(init, update, cfg)

    if t == "adadelta":
        rho, eps = cfg["rho"], cfg["eps"]

        def init(params):
            return {"eg": _zeros_like_tree(params), "edx": _zeros_like_tree(params)}

        def update(g, s, params, step):
            eg_new = _tmap(lambda e, gi: rho * e + (1 - rho) * gi * gi, s["eg"], g)
            upd = _tmap(
                lambda e, dx, gi: gi * jnp.sqrt(dx + eps) / jnp.sqrt(e + eps),
                eg_new,
                s["edx"],
                g,
            )
            edx_new = _tmap(lambda dx, u: rho * dx + (1 - rho) * u * u, s["edx"], upd)
            return upd, {"eg": eg_new, "edx": edx_new}

        return Updater(init, update, cfg)

    if t in ("adam", "adamax", "nadam", "amsgrad"):
        b1, b2, eps = cfg["beta1"], cfg["beta2"], cfg["eps"]

        def init(params):
            s = {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}
            if t == "amsgrad":
                s["vmax"] = _zeros_like_tree(params)
            return s

        def update(g, s, params, step):
            lr = lr_at(step)
            tt = jnp.asarray(step, jnp.float32) + 1.0
            bc1 = 1.0 - b1**tt
            bc2 = 1.0 - b2**tt
            m_new = _tmap(lambda mi, gi: b1 * mi + (1 - b1) * gi, s["m"], g)
            if t == "adamax":
                v_new = _tmap(lambda vi, gi: jnp.maximum(b2 * vi, jnp.abs(gi)), s["v"], g)
                upd = _tmap(lambda mi, vi: lr / bc1 * mi / (vi + eps), m_new, v_new)
                return upd, {"m": m_new, "v": v_new}
            v_new = _tmap(lambda vi, gi: b2 * vi + (1 - b2) * gi * gi, s["v"], g)
            if t == "amsgrad":
                vmax = _tmap(jnp.maximum, s["vmax"], v_new)
                upd = _tmap(
                    lambda mi, vi: lr * (mi / bc1) / (jnp.sqrt(vi / bc2) + eps), m_new, vmax
                )
                return upd, {"m": m_new, "v": v_new, "vmax": vmax}
            if t == "nadam":
                upd = _tmap(
                    lambda mi, vi, gi: lr
                    * (b1 * mi / bc1 + (1 - b1) * gi / bc1)
                    / (jnp.sqrt(vi / bc2) + eps),
                    m_new,
                    v_new,
                    g,
                )
                return upd, {"m": m_new, "v": v_new}
            upd = _tmap(
                lambda mi, vi: lr * (mi / bc1) / (jnp.sqrt(vi / bc2) + eps), m_new, v_new
            )
            return upd, {"m": m_new, "v": v_new}

        return Updater(init, update, cfg)

    raise AssertionError(t)


# ---------------------------------------------------------------------------
# Gradient normalization (reference: GradientNormalization enum, applied in
# BaseMultiLayerUpdater.preApply, nn/updater/BaseMultiLayerUpdater.java:322)
# ---------------------------------------------------------------------------


def apply_gradient_normalization(mode: Optional[str], threshold: float, layer_grads):
    """Apply one of DL4J's per-layer gradient normalization modes to a layer's
    grad dict (possibly nested). Returns the transformed grads."""
    if not mode or mode == "none":
        return layer_grads
    mode = str(mode).lower()
    eps = 1e-8

    leaves = jax.tree_util.tree_leaves(layer_grads)

    if mode == "renormalizel2perlayer" or mode == "renormalize_l2_per_layer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + eps)
        return _tmap(lambda g: g / norm, layer_grads)
    if mode == "renormalizel2perparamtype" or mode == "renormalize_l2_per_param_type":
        return _tmap(lambda g: g / jnp.sqrt(jnp.sum(g * g) + eps), layer_grads)
    if mode == "clipelementwiseabsolutevalue" or mode == "clip_elementwise_absolute_value":
        thr = float(threshold)
        return _tmap(lambda g: jnp.clip(g, -thr, thr), layer_grads)
    if mode == "clipl2perlayer" or mode == "clip_l2_per_layer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + eps)
        scale = jnp.minimum(1.0, threshold / norm)
        return _tmap(lambda g: g * scale, layer_grads)
    if mode == "clipl2perparamtype" or mode == "clip_l2_per_param_type":
        def clip(g):
            norm = jnp.sqrt(jnp.sum(g * g) + eps)
            return g * jnp.minimum(1.0, threshold / norm)

        return _tmap(clip, layer_grads)
    raise ValueError(f"Unknown gradient normalization mode '{mode}'")
