"""Training listeners: the hook SPI preserved from the reference.

Parity: optimize/api/TrainingListener.java + impls under optimize/listeners/
(ScoreIterationListener, PerformanceListener with samples/sec at :109,
CollectScoresIterationListener, TimeIterationListener, EvaluativeListener).

On TPU the listener fires on the HOST after each executed step; metrics it
receives are already-computed device scalars. Because the train step is one
XLA executable, listeners cannot observe intra-step activations the way the
reference's onForwardPass could — instead the model offers an explicit
``feed_forward`` debug path (interpret mode) for that use case.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Hook interface. All methods are optional no-ops."""

    def on_epoch_start(self, model, epoch: int):  # noqa: D102
        pass

    def on_epoch_end(self, model, epoch: int):  # noqa: D102
        pass

    def iteration_done(self, model, iteration: int, score: float, batch_size: int = 0):
        pass

    def on_gradient_calculation(self, model, iteration: int):
        pass


BaseTrainingListener = TrainingListener


class ScoreIterationListener(TrainingListener):
    """Log the score every N iterations (ScoreIterationListener.java)."""

    def __init__(self, print_every: int = 10, out: Optional[Callable[[str], None]] = None):
        self.print_every = max(1, print_every)
        self.out = out or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration, score, batch_size=0):
        if iteration % self.print_every == 0:
            self.out(f"Score at iteration {iteration} is {score}")


class PerformanceListener(TrainingListener):
    """Throughput reporting: samples/sec, batches/sec
    (PerformanceListener.java:109)."""

    def __init__(self, frequency: int = 10, out: Optional[Callable[[str], None]] = None):
        self.frequency = max(1, frequency)
        self.out = out or (lambda s: logger.info(s))
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._samples = 0
        self.history: List[dict] = []

    def iteration_done(self, model, iteration, score, batch_size=0):
        now = time.perf_counter()
        # anchor BEFORE accumulating: the anchoring call's batch used to be
        # discarded (_samples zeroed after += batch_size), understating
        # samples/sec for the first window
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
        self._samples += batch_size
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            rec = {
                "iteration": iteration,
                "batches_per_sec": iters / dt if dt > 0 else float("inf"),
                "samples_per_sec": self._samples / dt if dt > 0 else float("inf"),
                "score": score,
            }
            self.history.append(rec)
            self.out(
                f"iteration {iteration}: {rec['samples_per_sec']:.1f} samples/sec, "
                f"{rec['batches_per_sec']:.2f} batches/sec, score {score}"
            )
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0


class ProfilerListener(TrainingListener):
    """Capture a jax-profiler (xprof/perfetto) trace for a window of
    training iterations — §5.1 tracing parity; the reference's equivalent is
    the SystemInfo/benchmark tooling, here it is the real XLA profiler.

    Writes a TensorBoard-loadable trace directory::

        model.set_listeners(ProfilerListener("/tmp/trace", start=10, stop=20))
    """

    def __init__(self, log_dir: str, start: int = 10, stop: int = 20):
        if stop <= start:
            raise ValueError("stop must be > start")
        self.log_dir = str(log_dir)
        self.start = start
        self.stop = stop
        self._active = False
        self.captured = False

    def iteration_done(self, model, iteration, score, batch_size=0):
        import jax

        if not self._active and not self.captured and iteration >= self.start:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.stop:
            jax.profiler.stop_trace()
            self._active = False
            self.captured = True

    def close(self):
        """Stop an in-flight trace (call when training ends inside the
        window). Epoch boundaries deliberately do NOT stop the trace — a
        window may span epochs (1-iteration-per-epoch fits are common)."""
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self.captured = True


class CollectScoresListener(TrainingListener):
    """Accumulate (iteration, score) pairs
    (CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, score, batch_size=0):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class TimeIterationListener(TrainingListener):
    """ETA logging over a known iteration budget (TimeIterationListener.java)."""

    def __init__(self, total_iterations: int, frequency: int = 100,
                 out: Optional[Callable[[str], None]] = None):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self.out = out or (lambda s: logger.info(s))
        self.start = time.perf_counter()

    def iteration_done(self, model, iteration, score, batch_size=0):
        if iteration and iteration % self.frequency == 0:
            elapsed = time.perf_counter() - self.start
            rate = iteration / elapsed
            remaining = (self.total - iteration) / rate if rate > 0 else float("inf")
            self.out(f"iteration {iteration}/{self.total}, ETA {remaining:.0f}s")


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out set (EvaluativeListener.java)."""

    def __init__(self, data, frequency_epochs: int = 1,
                 out: Optional[Callable[[str], None]] = None):
        self.data = data
        self.frequency_epochs = max(1, frequency_epochs)
        self.out = out or (lambda s: logger.info(s))
        self.evaluations: List[object] = []

    def on_epoch_end(self, model, epoch):
        if epoch % self.frequency_epochs == 0:
            ev = model.evaluate(self.data)
            self.evaluations.append(ev)
            self.out(f"epoch {epoch}: accuracy {ev.accuracy():.4f} f1 {ev.f1():.4f}")


class ComposedListener(TrainingListener):
    """Fan out to several listeners."""

    def __init__(self, listeners: List[TrainingListener]):
        self.listeners = list(listeners)

    def on_epoch_start(self, model, epoch):
        for l in self.listeners:
            l.on_epoch_start(model, epoch)

    def on_epoch_end(self, model, epoch):
        for l in self.listeners:
            l.on_epoch_end(model, epoch)

    def iteration_done(self, model, iteration, score, batch_size=0):
        for l in self.listeners:
            l.iteration_done(model, iteration, score, batch_size)

    def on_gradient_calculation(self, model, iteration):
        for l in self.listeners:
            l.on_gradient_calculation(model, iteration)

    def close(self):
        close_listeners(self.listeners)


def close_listeners(listeners) -> None:
    """Call ``close()`` on every listener that defines one (fit teardown:
    stops in-flight ProfilerListener traces, flushes wrapped sinks). Errors
    are logged, not raised — teardown must not mask the fit's own outcome."""
    for l in listeners or ():
        close = getattr(l, "close", None)
        if callable(close):
            try:
                close()
            except Exception:
                logger.exception("listener %r close() failed", type(l).__name__)
