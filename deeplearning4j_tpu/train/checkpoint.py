"""Periodic checkpointing listener with retention policies.

Parity: optimize/listeners/checkpoint/CheckpointListener.java:72
(saveEveryNEpochs:83, saveEveryNIterations, saveEvery(time), keepAll,
keepLast:79, keepLastAndEvery:37-65) plus the static restore helpers
(loadCheckpoint, lastCheckpoint).

Durability (train/resilience.py): saves route through
``resilience.save_checkpoint`` — atomic zip write + full train state (RNG
key, batch position, LR scale, DP residuals) — and each index entry records
the file's CRC32 + size so ``last_valid_checkpoint`` can skip corrupt or
truncated files when resuming. Time-based saves use ``time.monotonic()``
(wall-clock steps must not suppress or duplicate saves).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import List, Optional

from deeplearning4j_tpu.train.listeners import TrainingListener


@dataclass
class Checkpoint:
    number: int
    iteration: int
    epoch: int
    timestamp: float
    filename: str
    crc: Optional[int] = None
    size: Optional[int] = None


class CheckpointListener(TrainingListener):
    """Save the model every N epochs / iterations / seconds; retention via
    keep_all / keep_last=k / keep_last_and_every=(k, n)."""

    INDEX = "checkpointInfo.json"

    def __init__(
        self,
        directory,
        save_every_n_epochs: Optional[int] = None,
        save_every_n_iterations: Optional[int] = None,
        save_every_seconds: Optional[float] = None,
        keep_all: bool = False,
        keep_last: Optional[int] = None,
        keep_last_and_every: Optional[tuple] = None,
        delete_existing: bool = False,
    ):
        if not (save_every_n_epochs or save_every_n_iterations or save_every_seconds):
            raise ValueError("Set one of save_every_n_epochs/_iterations/_seconds")
        if not keep_all and keep_last is None and keep_last_and_every is None:
            keep_last = 3
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        if delete_existing:
            for c in self.checkpoints(self.directory):
                try:
                    os.remove(os.path.join(self.directory, c.filename))
                except OSError:
                    pass
            idx = os.path.join(self.directory, self.INDEX)
            if os.path.exists(idx):
                os.remove(idx)
        self.save_every_n_epochs = save_every_n_epochs
        self.save_every_n_iterations = save_every_n_iterations
        self.save_every_seconds = save_every_seconds
        self.keep_all = keep_all
        self.keep_last = keep_last
        self.keep_last_and_every = keep_last_and_every
        self._last_save_time = time.monotonic()
        self._count = self._load_count()

    # -- listener hooks ----------------------------------------------------
    def iteration_done(self, model, iteration, score, batch_size=0):
        if (
            self.save_every_n_iterations
            and iteration > 0
            and iteration % self.save_every_n_iterations == 0
        ):
            self._save(model)
        elif self.save_every_seconds and (
            time.monotonic() - self._last_save_time >= self.save_every_seconds
        ):
            self._save(model)

    def on_epoch_end(self, model, epoch):
        if self.save_every_n_epochs and (epoch + 1) % self.save_every_n_epochs == 0:
            self._save(model)

    # -- mechanics ---------------------------------------------------------
    def _index_path(self):
        return os.path.join(self.directory, self.INDEX)

    def _load_count(self) -> int:
        if os.path.exists(self._index_path()):
            with open(self._index_path()) as f:
                entries = json.load(f)
            return (max(e["number"] for e in entries) + 1) if entries else 0
        return 0

    def _load_index(self) -> List[dict]:
        if os.path.exists(self._index_path()):
            with open(self._index_path()) as f:
                return json.load(f)
        return []

    def _save(self, model):
        from deeplearning4j_tpu.train import resilience

        num = self._count
        self._count += 1
        fname = f"checkpoint_{num}_iter_{model.iteration}_epoch_{model.epoch}.zip"
        path = os.path.join(self.directory, fname)
        info = resilience.save_checkpoint(model, path)
        entries = self._load_index()
        entries.append(
            {
                "number": num,
                "iteration": model.iteration,
                "epoch": model.epoch,
                "timestamp": time.time(),
                "filename": fname,
                "crc": info["crc"],
                "size": info["size"],
            }
        )
        self._write_index(entries)
        self._last_save_time = time.monotonic()
        # chaos corruption lands AFTER the CRC is recorded: validation, not
        # the write path, must be what catches the damaged file
        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_corrupt(path, num)
        self._apply_retention(entries)

    def _apply_retention(self, entries: List[dict]):
        if self.keep_all:
            return
        keep = set()
        if self.keep_last is not None:
            for e in entries[-self.keep_last :]:
                keep.add(e["number"])
        if self.keep_last_and_every is not None:
            k, every = self.keep_last_and_every
            for e in entries[-k:]:
                keep.add(e["number"])
            for e in entries:
                if e["number"] % every == 0:
                    keep.add(e["number"])
        remaining = []
        for e in entries:
            if e["number"] in keep:
                remaining.append(e)
            else:
                try:
                    os.remove(os.path.join(self.directory, e["filename"]))
                except OSError:
                    pass
        self._write_index(remaining)

    def _write_index(self, entries: List[dict]) -> None:
        """ATOMIC index write (temp + fsync + os.replace): a process killed
        mid-save — or a concurrent reader polling for resume — must never
        observe a truncated checkpointInfo.json (the preemption-recovery
        contract)."""
        tmp = self._index_path() + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(entries, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._index_path())

    # -- static inspection/restore helpers ---------------------------------
    @staticmethod
    def checkpoints(directory) -> List[Checkpoint]:
        idx = os.path.join(str(directory), CheckpointListener.INDEX)
        if not os.path.exists(idx):
            return []
        with open(idx) as f:
            return [Checkpoint(e["number"], e["iteration"], e["epoch"],
                               e["timestamp"], e["filename"],
                               e.get("crc"), e.get("size"))
                    for e in json.load(f)]

    @staticmethod
    def last_checkpoint(directory) -> Optional[Checkpoint]:
        cps = CheckpointListener.checkpoints(directory)
        return cps[-1] if cps else None

    @staticmethod
    def last_valid_checkpoint(directory) -> Optional[Checkpoint]:
        """Newest checkpoint whose file passes CRC/size (or structural)
        validation — corrupt or truncated files fall through to older ones."""
        from deeplearning4j_tpu import obs
        from deeplearning4j_tpu.train import resilience

        for c in reversed(CheckpointListener.checkpoints(directory)):
            path = os.path.join(str(directory), c.filename)
            if resilience.validate_checkpoint(path, crc=c.crc, size=c.size):
                return c
            obs.event("checkpoint_corrupt_fallback", path=path,
                      number=c.number)
        return None

    @staticmethod
    def load_checkpoint(directory, number: int):
        from deeplearning4j_tpu.utils.serialization import restore_network

        for c in CheckpointListener.checkpoints(directory):
            if c.number == number:
                return restore_network(os.path.join(str(directory), c.filename))
        raise FileNotFoundError(f"No checkpoint #{number} in {directory}")

    @staticmethod
    def load_last_checkpoint(directory):
        c = CheckpointListener.last_checkpoint(directory)
        if c is None:
            raise FileNotFoundError(f"No checkpoints in {directory}")
        from deeplearning4j_tpu.utils.serialization import restore_network

        return restore_network(os.path.join(str(directory), c.filename))

    @staticmethod
    def load_last_valid_checkpoint(directory):
        c = CheckpointListener.last_valid_checkpoint(directory)
        if c is None:
            raise FileNotFoundError(f"No valid checkpoints in {directory}")
        from deeplearning4j_tpu.utils.serialization import restore_network

        return restore_network(os.path.join(str(directory), c.filename))
