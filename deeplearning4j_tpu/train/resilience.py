"""Fault-tolerant training runtime: durable checkpoints, auto-resume,
divergence guard, and a deterministic chaos-injection harness.

The reference stack survives long runs through CheckpointListener retention
policies and early-stopping restores; a preempted TPU job additionally needs
the pieces a model zip alone does not carry — the RNG key driving per-batch
dropout streams, the iterator position inside the epoch, the LR backoff
scale, and the PR-3 compression residuals riding the donated opt carry. This
module owns that full-state contract:

- ``save_checkpoint`` / ``validate_checkpoint``: atomic zip writes
  (tmp + fsync + ``os.replace`` in utils/serialization.py) with a CRC32 +
  size recorded in ``checkpointInfo.json``, so a checkpoint is either whole
  or provably bad.
- ``resume(model, dir)``: load the NEWEST VALID checkpoint (corrupt/truncated
  files fall back to the previous valid one) into an existing model —
  params, optimizer state, BN state, iteration/epoch, RNG key,
  batch-in-epoch position, LR scale, and DP residuals. ``fit(...,
  resume_from=dir)`` on MLN/CG/ParallelWrapper drives this and skips the
  already-consumed batches of the interrupted epoch, so an interrupted +
  resumed run replays the exact same RNG/batch stream as an uninterrupted
  one (bit-exact on CPU; tests/test_resilience.py).
- ``DivergenceGuard``: non-finite / loss-spike detection. The ``skip_batch``
  policy is applied INSIDE the compiled step (``guard_ok``/``guard_select``
  below — a ``jnp.where`` select between the candidate and previous
  params/opt/state, no extra host sync); the host side batches its score
  reads (``flush_every`` window) so warn/skip never add per-step syncs.
  ``rollback`` reloads the last valid checkpoint and applies a capped LR
  backoff.
- Chaos harness: ``DL4J_TPU_CHAOS=preempt@iter:8,corrupt_ckpt@ckpt:1:bitflip``
  style fault grammar (see ``ChaosInjector.parse``) injecting
  kill-at-iteration, checkpoint corruption, NaN gradients (NaN-poisoned
  batches), and stalled iterations — deterministic and one-shot per fault,
  so tests and ``tools/chaos_smoke.sh`` can prove recovery end to end.

See docs/ROBUSTNESS.md for the checkpoint format and recovery semantics.
"""

from __future__ import annotations

import glob
import io
import json
import math
import os
import signal
import time
import warnings
import zipfile
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.utils import bucketing

__all__ = [
    "ChaosInjector",
    "ChaosPreemption",
    "DivergenceError",
    "DivergenceGuard",
    "active_chaos",
    "capture_train_state",
    "crc32_file",
    "install_chaos",
    "io_with_retries",
    "load_distributed_checkpoint",
    "load_state_into",
    "note_score",
    "resume",
    "save_checkpoint",
    "validate_checkpoint",
    "write_bytes_durable",
    "write_json_durable",
]


# ---------------------------------------------------------------------------
# Retrying I/O: bounded exponential backoff for checkpoint reads/writes
# ---------------------------------------------------------------------------


def _retry_knobs():
    return (int(os.environ.get("DL4J_TPU_CKPT_RETRIES", "3")),
            float(os.environ.get("DL4J_TPU_CKPT_RETRY_BASE_S", "0.05")),
            float(os.environ.get("DL4J_TPU_CKPT_RETRY_CAP_S", "2.0")))


def io_with_retries(fn: Callable[[], Any], *, what: str = "ckpt_io"):
    """Run a checkpoint I/O callable, retrying ``OSError`` with bounded
    exponential backoff (``DL4J_TPU_CKPT_RETRIES`` attempts beyond the
    first, delay ``base * 2**k`` capped at ``DL4J_TPU_CKPT_RETRY_CAP_S``).
    Network filesystems fail transiently under exactly the membership churn
    the elastic runtime is built for; each retry increments
    ``dl4j_ckpt_retries_total``. Exhaustion re-raises the last error."""
    retries, base, cap = _retry_knobs()
    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError as e:
            if attempt >= retries:
                raise
            delay = min(base * (2 ** attempt), cap)
            obs.counter("dl4j_ckpt_retries_total",
                        "Checkpoint I/O operations retried after a "
                        "transient OSError").inc()
            obs.event("ckpt_io_retry", what=what, attempt=attempt + 1,
                      error=str(e), delay_s=round(delay, 4))
            time.sleep(delay)


def write_bytes_durable(path, data: bytes) -> None:
    """Atomic durable byte write (tmp + fsync + ``os.replace``) with retry
    backoff — the primitive under the distributed checkpoint shards."""

    def attempt():
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    io_with_retries(attempt, what=f"write:{os.path.basename(str(path))}")


def write_json_durable(path, value) -> None:
    write_bytes_durable(path, json.dumps(value, indent=1).encode("utf-8"))


# ---------------------------------------------------------------------------
# Durable checkpoints: CRC + validation + newest-valid fallback
# ---------------------------------------------------------------------------


def crc32_file(path, chunk: int = 1 << 20) -> int:
    """CRC32 of a file's bytes, streamed (checkpoints can be large)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def validate_checkpoint(path, crc: Optional[int] = None,
                        size: Optional[int] = None) -> bool:
    """True when the checkpoint file at ``path`` is intact.

    With a recorded ``crc``/``size`` (checkpointInfo.json entries) the check
    is exact: truncation changes the size, bit flips change the CRC. Legacy
    entries without a CRC fall back to a structural zip check (central
    directory + per-entry CRCs + required entries present)."""
    try:
        if not os.path.isfile(path):
            return False
        if size is not None and os.path.getsize(path) != int(size):
            return False
        if crc is not None:
            return crc32_file(path) == int(crc)
        from deeplearning4j_tpu.utils import serialization as S

        with zipfile.ZipFile(path, "r") as zf:
            if zf.testzip() is not None:
                return False
            names = set(zf.namelist())
            return S.CONFIG_ENTRY in names and S.COEFFICIENTS_ENTRY in names
    except Exception:
        return False


def capture_train_state(model) -> dict:
    """The JSON-able training state a model zip alone does not carry: RNG
    key (per-batch dropout/noise stream position), batch-in-epoch iterator
    position, divergence-guard LR scale, and the full observability snapshot
    — metrics, span aggregates, event counts, bucketing counters
    (informational — restored runs keep their own counters)."""
    state: Dict[str, Any] = {
        "version": 1,
        "batch_in_epoch": int(getattr(model, "batch_in_epoch", 0)),
        "lr_scale": float(getattr(model, "_lr_scale", 1.0)),
        "telemetry": obs.snapshot(),
    }
    rng = getattr(model, "_rng", None)
    if rng is not None:
        arr = np.asarray(rng)  # graftlint: disable=host-sync
        state["rng"] = arr.tolist()
        state["rng_dtype"] = str(arr.dtype)
    return state


def save_checkpoint(model, path, normalizer: Optional[dict] = None) -> dict:
    """Durable full-state checkpoint: atomic zip write + CRC over the final
    bytes. When a DataParallelStep is active on the model, the optimizer
    state is snapshotted OUT of the flat ``[R, m]`` exchange layout (the
    model's structured copy is stale mid-fit) and the per-replica
    compression residuals are captured alongside. Returns
    ``{"path", "crc", "size"}`` for the checkpoint index."""
    from deeplearning4j_tpu.utils import serialization as S

    t0 = time.perf_counter()
    with obs.span("checkpoint.save"):
        opt_state = None
        residuals = None
        runner = getattr(model, "_dp_runner", None)
        if runner is not None:
            if getattr(runner, "_active", False):
                opt_state = runner.snapshot_opt_state()
            residuals = runner.export_residuals() or None
        io_with_retries(
            lambda: S.save_network(model, path, normalizer=normalizer,
                                   train_state=capture_train_state(model),
                                   residuals=residuals, opt_state=opt_state),
            what=f"save_network:{os.path.basename(str(path))}")
        info = {"path": path, "crc": crc32_file(path),
                "size": os.path.getsize(path)}
    dur = time.perf_counter() - t0
    obs.counter("dl4j_checkpoint_saves_total",
                "Checkpoints written via save_checkpoint").inc()
    obs.histogram("dl4j_checkpoint_save_seconds",
                  "Wall time of durable checkpoint writes").observe(dur)
    obs.event("checkpoint_saved", path=str(path), crc=info["crc"],
              size=info["size"], duration_s=round(dur, 6))
    # executable bundle sidecar (nn/aot.py): resume restores params AND
    # compiled executables. save_bundle gates itself (validation-proven
    # backends only; default off on XLA:CPU) and never raises — the
    # checkpoint above is durable regardless of what happens here.
    from deeplearning4j_tpu.nn import aot

    bundle = aot.save_bundle(model, aot.bundle_path_for(path))
    if bundle is not None:
        info["aot_bundle"] = bundle
    return info


def load_state_into(model, path):
    """Load a checkpoint INTO an existing (config-compatible) model:
    params/state/opt plus the train-state extras. Leaf-count mismatches
    raise (config/checkpoint mismatch) rather than silently truncating."""
    from deeplearning4j_tpu.utils import serialization as S

    t0 = time.perf_counter()
    with obs.span("checkpoint.restore"):
        if model.params is None:
            model.init()
        S.apply_snapshot(model, S.read_snapshot(path))
    dur = time.perf_counter() - t0
    obs.counter("dl4j_checkpoint_restores_total",
                "Checkpoints loaded via load_state_into/resume").inc()
    obs.histogram("dl4j_checkpoint_restore_seconds",
                  "Wall time of checkpoint restores").observe(dur)
    obs.event("checkpoint_restored", path=str(path), duration_s=round(dur, 6))
    return model


def resume(model, directory):
    """Restore ``model`` from the newest VALID checkpoint in ``directory``
    (corrupt/truncated files fall back to older valid ones). Returns the
    Checkpoint record, or None (with a warning) when the directory holds no
    valid checkpoint — training then starts from the model's current state."""
    from deeplearning4j_tpu.train.checkpoint import CheckpointListener

    cp = CheckpointListener.last_valid_checkpoint(directory)
    if cp is None:
        obs.event("checkpoint_corrupt_fallback", directory=str(directory),
                  fallback="none")
        warnings.warn(
            f"resume_from={str(directory)!r}: no valid checkpoint found; "
            "training from the model's current state")
        return None
    path = os.path.join(str(directory), cp.filename)
    load_state_into(model, path)
    # executable bundle sidecar: restore compiled executables alongside the
    # params so the first post-resume step/request is warm. Missing file is
    # a silent no-op; corrupt/mismatched bundles reject to recompile
    # (never raise) — see nn/aot.py.
    from deeplearning4j_tpu.nn import aot

    aot.restore_bundle(model, aot.bundle_path_for(path))
    return cp


# ---------------------------------------------------------------------------
# Distributed checkpoints (elastic multi-host layout)
# ---------------------------------------------------------------------------


def load_distributed_checkpoint(directory) -> Optional[dict]:
    """Load the newest VALID distributed checkpoint from ``directory``.

    The elastic trainer's layout (docs/ROBUSTNESS.md): per-host shard files
    ``shard_<tag>_r<rank>.npz`` (each rank's optimizer segments — its
    primary 1/W slice AND its buddy's mirror — plus compression residuals),
    a replicated ``ckpt_<tag>_params.npz`` (params, dense opt state, layer
    state, meta), and a ``manifest_<tag>.json`` with per-file CRC32 + size
    written LAST by rank 0 — the commit point.

    Validation is per-file: a manifest whose params file fails its CRC falls
    back to the next-older manifest; a corrupt *shard* file is dropped
    individually, because every segment it held also lives in its buddy's
    shard (any host can serve a straggler's shard) — only the trainer can
    judge whether the surviving set covers every segment. Returns
    ``{"manifest", "params", "shards": {rank: arrays}, "path"}`` or None.
    """
    directory = os.fspath(directory)
    manifests = sorted(glob.glob(os.path.join(directory, "manifest_*.json")),
                       reverse=True)
    for mpath in manifests:
        try:
            with open(mpath, "r") as f:
                man = json.load(f)
        except (OSError, ValueError):
            obs.event("checkpoint_corrupt_fallback", path=mpath,
                      reason="manifest unreadable")
            continue
        ppath = os.path.join(directory, man["params"]["file"])
        if not validate_checkpoint(ppath, crc=man["params"]["crc"],
                                   size=man["params"]["size"]):
            obs.event("checkpoint_corrupt_fallback", path=ppath,
                      reason="params file failed CRC/size")
            continue
        pdata = io_with_retries(
            lambda: open(ppath, "rb").read(), what="read:params")
        with np.load(io.BytesIO(pdata), allow_pickle=False) as z:
            params = {k: z[k] for k in z.files}
        shards: Dict[int, Dict[str, np.ndarray]] = {}
        for rank_s, meta in man.get("shards", {}).items():
            spath = os.path.join(directory, meta["file"])
            if not validate_checkpoint(spath, crc=meta["crc"],
                                       size=meta["size"]):
                obs.event("checkpoint_shard_dropped", path=spath,
                          rank=int(rank_s), reason="failed CRC/size")
                continue
            sdata = io_with_retries(
                lambda p=spath: open(p, "rb").read(),
                what=f"read:shard{rank_s}")
            with np.load(io.BytesIO(sdata), allow_pickle=False) as z:
                shards[int(rank_s)] = {k: z[k] for k in z.files}
        obs.event("distributed_checkpoint_loaded", path=mpath,
                  world=man.get("world"), shards=sorted(shards),
                  iteration=man.get("iteration"))
        return {"manifest": man, "params": params, "shards": shards,
                "path": mpath}
    return None


# ---------------------------------------------------------------------------
# Divergence guard
# ---------------------------------------------------------------------------


class DivergenceError(RuntimeError):
    """Raised when the rollback policy exhausts its retry budget (or has no
    valid checkpoint to roll back to)."""


def guard_ok(loss, spike_limit: Optional[float]):
    """Traced predicate: the step's candidate update is acceptable. Runs
    INSIDE the compiled step (device-side; replicated under shard_map since
    the loss is already the replica mean)."""
    ok = jnp.isfinite(loss)
    if spike_limit is not None:
        ok = ok & (loss <= jnp.asarray(spike_limit, loss.dtype))
    return ok


def guard_select(ok, new_tree, old_tree):
    """Traced per-leaf select: keep the candidate when ``ok``, else the
    previous value — the skip_batch policy's whole mechanism, fused into the
    same executable as the step (donation-safe)."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(ok, a, b), new_tree, old_tree)


class DivergenceGuard:
    """Non-finite / loss-spike watchdog for fit loops.

    Policies (``InvalidScoreIterationTerminationCondition`` semantics,
    upgraded from terminate-only to recover):

    - ``warn``: count + warn-once; training proceeds untouched.
    - ``skip_batch``: the compiled step discards the bad update on device
      (``guard_ok``/``guard_select``); the host side only counts/warns.
    - ``rollback``: reload the last valid checkpoint from
      ``checkpoint_dir``, multiply the LR by ``lr_backoff`` (compounding),
      and continue — at most ``max_retries`` times, then
      :class:`DivergenceError`.

    Host syncs: warn/skip batch their score reads in windows of
    ``flush_every`` device scalars (ONE stacked transfer per window, flushed
    again at epoch end) so the guard adds no per-step sync. rollback
    necessarily syncs every step — it must act before the next update.

    Install with ``model.set_divergence_guard(guard)`` (clears the compiled
    step caches: skip_batch is traced into the step).
    """

    POLICIES = ("warn", "skip_batch", "rollback")

    def __init__(self, policy: str = "warn", spike_limit: Optional[float] = None,
                 checkpoint_dir=None, lr_backoff: float = 0.5,
                 max_retries: int = 3, flush_every: int = 32):
        if policy not in self.POLICIES:
            raise ValueError(
                f"DivergenceGuard policy {policy!r} not in {self.POLICIES}")
        if policy == "rollback" and checkpoint_dir is None:
            raise ValueError(
                "DivergenceGuard(policy='rollback') needs checkpoint_dir=")
        self.policy = policy
        self.spike_limit = None if spike_limit is None else float(spike_limit)
        self.checkpoint_dir = checkpoint_dir
        self.lr_backoff = float(lr_backoff)
        self.max_retries = int(max_retries)
        self.flush_every = max(int(flush_every), 1)
        self.trips = 0
        self.retries = 0
        self._pending: List[Any] = []
        self._warned = False

    def _bad_value(self, v: float) -> bool:
        return (not math.isfinite(v)) or (
            self.spike_limit is not None and v > self.spike_limit)

    def observe(self, model, score) -> None:
        """Feed one step's score (device scalar or float) from the fit loop."""
        if self.policy == "rollback":
            v = float(score)  # graftlint: disable=host-sync
            if self._bad_value(v):
                self._trip(model, v)
            return
        self._pending.append(score)
        if len(self._pending) >= self.flush_every:
            self.flush(model)

    def flush(self, model) -> None:
        """Sync the pending window as ONE stacked transfer and act on it."""
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        stacked = jnp.stack([jnp.asarray(v, jnp.float32) for v in pend])
        vals = np.asarray(stacked)  # graftlint: disable=host-sync
        bad = ~np.isfinite(vals)
        if self.spike_limit is not None:
            bad |= vals > self.spike_limit
        if bad.any():
            self._trip(model, float(vals[bad][0]))

    def _trip(self, model, value: float) -> None:
        self.trips += 1
        bucketing.telemetry().record_guard(self.policy)
        obs.event("divergence", policy=self.policy, score=repr(value),
                  trips=self.trips)
        if not self._warned:
            self._warned = True
            warnings.warn(
                f"DivergenceGuard: non-finite or spiking training score "
                f"{value!r} (policy={self.policy}, trip #{self.trips}); see "
                "docs/ROBUSTNESS.md")
        if self.policy != "rollback":
            return
        if self.retries >= self.max_retries:
            raise DivergenceError(
                f"divergence persisted through {self.retries} rollback "
                f"retries (last score {value!r})")
        self.retries += 1
        if resume(model, self.checkpoint_dir) is None:
            raise DivergenceError(
                f"cannot roll back: no valid checkpoint in "
                f"{str(self.checkpoint_dir)!r}")
        # compounding backoff on top of whatever scale the checkpoint carried
        model._lr_scale = getattr(model, "_lr_scale", 1.0) * self.lr_backoff
        model._build_updaters()
        if hasattr(model, "_clear_compiled"):
            model._clear_compiled()
        runner = getattr(model, "_dp_runner", None)
        if runner is not None and getattr(runner, "_active", False):
            runner.reload()
        bucketing.telemetry().record_guard("rollback_restore")
        obs.event("rollback_restore", retries=self.retries,
                  lr_scale=float(model._lr_scale))


_INVALID_SCORE_WARNED = False


def note_score(score: float) -> None:
    """InvalidScoreIterationTerminationCondition semantics on the DEFAULT fit
    path: when the already-synced listener score goes non-finite, count it in
    the bucketing telemetry snapshot and warn once (pointing at the guard
    policies that can act on it). Costs nothing — the score was synced for
    the listeners anyway."""
    if math.isfinite(score):
        return
    bucketing.telemetry().record_guard("invalid_score")
    obs.event("invalid_score", score=repr(score))
    global _INVALID_SCORE_WARNED
    if not _INVALID_SCORE_WARNED:
        _INVALID_SCORE_WARNED = True
        warnings.warn(
            f"training score became non-finite ({score!r}). Attach "
            "DivergenceGuard(policy='skip_batch'|'rollback') via "
            "model.set_divergence_guard(...) to recover automatically, or an "
            "early-stopping InvalidScoreIterationTerminationCondition to "
            "terminate (docs/ROBUSTNESS.md)")


# ---------------------------------------------------------------------------
# Chaos harness
# ---------------------------------------------------------------------------


class ChaosPreemption(RuntimeError):
    """Raised by the chaos injector to simulate a preemption (the in-process
    flavor of kill; ``preempt@iter:K:kill`` sends a real SIGKILL instead)."""


@dataclass
class _Fault:
    kind: str
    at_iter: Optional[int] = None
    at_ckpt: Optional[int] = None
    arg: Optional[str] = None
    fired: bool = False


_FAULT_KINDS = ("preempt", "corrupt_ckpt", "nan_grad", "slow_iter",
                "host_kill", "net_partition", "slice_kill",
                "rack_partition")


def _parse_fault(token: str) -> _Fault:
    name, at_iter, at_ckpt, arg = token, None, None, None
    if "@" in token:
        name, rest = token.split("@", 1)
        parts = rest.split(":")
        if len(parts) < 2 or not parts[1]:
            raise ValueError(
                f"chaos fault {token!r}: anchor must be @iter:K or @ckpt:K")
        where, val = parts[0], parts[1]
        # args may themselves contain ':' (e.g. net_partition's rank1:4.0)
        arg = ":".join(parts[2:]) or None
        if where == "iter":
            at_iter = int(val)
        elif where == "ckpt":
            at_ckpt = int(val)
        else:
            raise ValueError(
                f"chaos fault {token!r}: unknown anchor @{where} "
                "(use @iter:K or @ckpt:K)")
    elif ":" in token:
        name, arg = token.split(":", 1)
    if name not in _FAULT_KINDS:
        raise ValueError(
            f"chaos fault {token!r}: unknown kind {name!r} "
            f"(known: {', '.join(_FAULT_KINDS)})")
    return _Fault(kind=name, at_iter=at_iter, at_ckpt=at_ckpt, arg=arg)


def _nan_like(x):
    """NaN-poison float members of a batch (integer token-id features cannot
    hold NaN and pass through untouched)."""
    if x is None:
        return None
    if isinstance(x, (tuple, list)):
        return type(x)(_nan_like(a) for a in x)
    dt = getattr(x, "dtype", None)
    if dt is None:
        x = np.asarray(x)
        dt = x.dtype
    if jnp.issubdtype(jnp.dtype(dt), jnp.floating):
        # multiply (not fill): preserves shape, dtype, AND device sharding
        return jnp.asarray(x) * jnp.asarray(float("nan"), jnp.dtype(dt))
    return x


def corrupt_file(path, mode: str = "bitflip") -> None:
    """Deterministically damage a file in place: ``truncate`` halves it
    (size mismatch), ``bitflip`` XORs one mid-file byte (CRC mismatch at an
    unchanged size)."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return
    if mode != "bitflip":
        raise ValueError(f"corrupt_ckpt arg {mode!r}: use truncate|bitflip")
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([(b[0] ^ 0x40) if b else 0xFF]))


class ChaosInjector:
    """Deterministic fault injector. Grammar (``DL4J_TPU_CHAOS``):

    comma-separated faults, each ``kind[@iter:K|@ckpt:K][:arg]``:

    - ``preempt@iter:K[:kill]`` — die before the step whose iteration
      counter is >= K runs: raise :class:`ChaosPreemption` (default) or send
      a real SIGKILL (``:kill``). Fires once per process.
    - ``nan_grad[@iter:K]`` — NaN-poison the batch features of iteration K
      (every float activation/gradient downstream goes NaN). Fires once.
    - ``slow_iter[@iter:K][:rankN][:seconds]`` — sleep before the step
      (default 0.05 s); without an anchor, every step (a stalled iterator).
      A ``rankN`` target limits the stall to one data-parallel rank — the
      deterministic straggler the fleet skew detector must flag.
    - ``corrupt_ckpt[@ckpt:K][:truncate|bitflip]`` — damage checkpoint
      number K (or the first one written) AFTER its CRC is recorded, so
      validation must catch it. Fires once.
    - ``host_kill@iter:K[:rankN]`` — the distributed flavor of kill: SIGKILL
      the process before the step whose iteration is >= K, only when this
      worker's data-parallel rank matches the ``rankN`` target (no target:
      every rank that consults the hook). Fires once; drives the elastic
      shrink path (tests/test_elastic.py, tools/elastic_smoke.sh).
    - ``net_partition@iter:K[:rankN][:seconds]`` — simulate this worker
      landing on the wrong side of a switch: the elastic runtime suspends
      its lease heartbeat and stalls for ``seconds`` (default 5.0). A stall
      longer than the lease TTL gets the worker expelled; on waking it
      renews its lease and rejoins through the membership handoff.
    - ``slice_kill@iter:K[:sliceN]`` — the fleet-scale flavor of kill: in
      the elastic-of-slices composition each member process IS one
      ``(d,t,s)`` mesh slice (member = slice coordinator), so a slice
      preemption is one SIGKILL of the member whose slice index (= elastic
      rank) matches ``sliceN`` (no target: every slice that consults the
      hook). One membership event per slice, not per chip.
    - ``rack_partition@iter:K[:LABEL][:seconds]`` — ``net_partition`` for a
      whole rack: every worker whose ``DL4J_TPU_RACK`` label equals
      ``LABEL`` (no label: all workers) suspends its heartbeat and stalls
      for ``seconds`` (default 5.0) — the R-way rack-aware mirrors must
      carry every optimizer segment whose owner sat in that rack.

    Faults are host-side and one-shot: a resumed run that re-executes the
    target iteration is NOT re-hit (the process that resumed carries a fresh
    injector only if the spec is still installed — clear the env var /
    ``install_chaos(None)`` for clean resumes).
    """

    def __init__(self, faults, spec: str = ""):
        self.faults = list(faults)
        self.spec = spec

    @staticmethod
    def parse(spec: str) -> "ChaosInjector":
        faults = [_parse_fault(t.strip()) for t in spec.split(",") if t.strip()]
        return ChaosInjector(faults, spec)

    # -- per-iteration hooks (fit dispatch paths) ---------------------------
    def maybe_preempt(self, iteration: int) -> None:
        for f in self.faults:
            if (f.kind == "preempt" and not f.fired
                    and f.at_iter is not None and iteration >= f.at_iter):
                f.fired = True
                obs.event("chaos", fault="preempt", iteration=iteration,
                          arg=f.arg)
                if f.arg == "kill":
                    os.kill(os.getpid(), signal.SIGKILL)
                raise ChaosPreemption(
                    f"chaos: preempted at iteration {iteration}")

    def maybe_slow(self, iteration: int, *, rank: Optional[int] = None) -> None:
        for f in self.faults:
            if f.kind != "slow_iter":
                continue
            # rank-targeted straggler injection (``slow_iter:rank1:0.5``):
            # only the targeted data-parallel rank stalls, so the skew is
            # attributable — the straggler detector's test fixture
            target, rest = self._rank_arg(f.arg)
            if target is not None and (rank is None or rank != target):
                continue
            if f.at_iter is None or (iteration == f.at_iter and not f.fired):
                if f.at_iter is not None:
                    f.fired = True
                    obs.event("chaos", fault="slow_iter", iteration=iteration,
                              rank=rank)
                time.sleep(float(rest) if rest else 0.05)

    def maybe_nan_batch(self, iteration: int, x):
        for f in self.faults:
            if f.kind != "nan_grad" or f.fired:
                continue
            if f.at_iter is None or iteration == f.at_iter:
                f.fired = True
                obs.event("chaos", fault="nan_grad", iteration=iteration)
                return _nan_like(x)
        return x

    # -- distributed hooks (ElasticTrainer step boundary) -------------------
    @staticmethod
    def _prefixed_arg(arg: Optional[str], prefix: str):
        """Split a fault arg into (target_index, rest) for a ``<prefix>N``
        head: ``rank1:4.0`` -> (1, "4.0"), ``slice2`` -> (2, None), a
        non-matching head -> (None, arg)."""
        if not arg:
            return None, None
        head, _, rest = arg.partition(":")
        if head.startswith(prefix) and head[len(prefix):].isdigit():
            return int(head[len(prefix):]), (rest or None)
        return None, arg

    @staticmethod
    def _rank_arg(arg: Optional[str]):
        """Split a fault arg into (target_rank, rest): ``rank1:4.0`` ->
        (1, "4.0"), ``rank2`` -> (2, None), ``3.5`` -> (None, "3.5")."""
        return ChaosInjector._prefixed_arg(arg, "rank")

    def maybe_host_kill(self, iteration: int, *, rank: int) -> None:
        for f in self.faults:
            if (f.kind != "host_kill" or f.fired or f.at_iter is None
                    or iteration < f.at_iter):
                continue
            target, _ = self._rank_arg(f.arg)
            if target is not None and target != rank:
                continue
            f.fired = True
            obs.event("chaos", fault="host_kill", iteration=iteration,
                      rank=rank)
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_slice_kill(self, iteration: int, *, slice_index: int) -> None:
        """SIGKILL this member process when a ``slice_kill`` fault targets
        its slice index — one whole-slice preemption, one membership
        event (the member process carries the entire slice mesh)."""
        for f in self.faults:
            if (f.kind != "slice_kill" or f.fired or f.at_iter is None
                    or iteration < f.at_iter):
                continue
            target, _ = self._prefixed_arg(f.arg, "slice")
            if target is not None and target != slice_index:
                continue
            f.fired = True
            obs.event("slice_kill", iteration=iteration, slice=slice_index)
            obs.event("chaos", fault="slice_kill", iteration=iteration,
                      slice=slice_index)
            os.kill(os.getpid(), signal.SIGKILL)

    def rack_partition_seconds(self, iteration: int, *, rack: str) -> float:
        """Non-zero when a ``rack_partition`` fault hits this worker's rack
        label at this iteration; the caller owns the mechanics (suspend
        heartbeat + stall), same as :meth:`partition_seconds`."""
        for f in self.faults:
            if (f.kind != "rack_partition" or f.fired or f.at_iter is None
                    or iteration < f.at_iter):
                continue
            label: Optional[str] = None
            secs = 5.0
            if f.arg:
                head, _, rest = f.arg.partition(":")
                try:
                    secs = float(head)   # bare seconds: every rack
                except ValueError:
                    label = head
                    if rest:
                        secs = float(rest)
            if label is not None and label != rack:
                continue
            f.fired = True
            obs.event("chaos", fault="rack_partition", iteration=iteration,
                      rack=rack, seconds=secs)
            return secs
        return 0.0

    def partition_seconds(self, iteration: int, *, rank: int) -> float:
        """Non-zero when a ``net_partition`` fault targets this (iteration,
        rank); the caller owns the mechanics (suspend heartbeat + stall)."""
        for f in self.faults:
            if (f.kind != "net_partition" or f.fired or f.at_iter is None
                    or iteration < f.at_iter):
                continue
            target, rest = self._rank_arg(f.arg)
            if target is not None and target != rank:
                continue
            f.fired = True
            obs.event("chaos", fault="net_partition", iteration=iteration,
                      rank=rank, seconds=rest)
            return float(rest) if rest else 5.0
        return 0.0

    # -- checkpoint hook (CheckpointListener._save) -------------------------
    def maybe_corrupt(self, path, ckpt_number: int) -> None:
        for f in self.faults:
            if f.kind != "corrupt_ckpt" or f.fired:
                continue
            if f.at_ckpt is None or ckpt_number == f.at_ckpt:
                f.fired = True
                obs.event("chaos", fault="corrupt_ckpt", path=str(path),
                          mode=f.arg or "bitflip")
                corrupt_file(path, mode=f.arg or "bitflip")


_UNSET = object()
_chaos_override: Any = _UNSET
_env_injectors: Dict[str, ChaosInjector] = {}


def install_chaos(spec):
    """Programmatic chaos install (wins over ``DL4J_TPU_CHAOS``). Pass a
    grammar string or a :class:`ChaosInjector`; ``None`` clears the override
    (the environment variable rules again). Returns the active injector."""
    global _chaos_override
    if spec is None:
        _chaos_override = _UNSET
        return None
    inj = spec if isinstance(spec, ChaosInjector) else ChaosInjector.parse(spec)
    _chaos_override = inj
    return inj


def active_chaos() -> Optional[ChaosInjector]:
    """The installed injector, the env-configured one, or None. The env
    injector is cached per spec string so one-shot faults stay one-shot
    across the many hooks that consult it."""
    if _chaos_override is not _UNSET:
        return _chaos_override
    spec = os.environ.get("DL4J_TPU_CHAOS")
    if not spec:
        return None
    inj = _env_injectors.get(spec)
    if inj is None:
        inj = ChaosInjector.parse(spec)
        _env_injectors[spec] = inj
    return inj
