"""Device-resident ANN search tier (docs/SEARCH.md).

The retrieval layer of the port (reference: VPTree/KD-tree/LSH + the
NearestNeighborsServer, PAPER.md layer 6), rebuilt accelerator-first:
instead of pointer-chasing tree structures, three matmul-shaped scoring
tiers (exact / IVF / IVF-PQ) over a fixed-shape device corpus, compiled
once per bucket rung and served through the same admission/SLO machinery
as every other route (``serve/``).
"""

from deeplearning4j_tpu.search.index import IndexConfig, VectorIndex
from deeplearning4j_tpu.search.program import (
    SITE_EXACT, SITE_IVF, SITE_MERGE, SITE_PQ, SearchProgram,
)

__all__ = [
    "IndexConfig", "SITE_EXACT", "SITE_IVF", "SITE_MERGE", "SITE_PQ",
    "SearchProgram", "VectorIndex",
]
