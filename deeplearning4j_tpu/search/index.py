"""Device-resident vector index: build / search / persist lifecycle.

:class:`VectorIndex` owns the device state the compiled tiers
(``search/program.py``) score against — a fixed-capacity corpus array, the
IVF centroids/postings from ``clustering/kmeans.py``, optional PQ codes —
plus the host-side lifecycle around it:

- **build**: train the coarse quantizer on a subsample (random-init Lloyd —
  k-means++ is O(n·k²) distance work, pointless when Lloyd refines anyway),
  assign the full corpus through the bucketed ``kmeans.assign`` site, lay
  postings out as a padded [nlist, L] table, optionally train per-subspace
  PQ codebooks and encode. Every device array is padded to a bucket rung so
  the kernel signature grid is finite and warmable.
- **search**: pad the query batch up the shared ladder, dispatch the
  requested tier, merge the pending buffer's exact scores, slice back to
  the real rows/k — bit-exact under coalescing because every op is
  row-independent and column-slicing a top-k result is stable.
- **incremental adds**: a fixed-shape pending buffer is searchable
  immediately (exact tier + device merge); ``merge_pending`` folds it into
  the main structure off the hot path (an admin operation that may grow
  capacity and therefore compile).
- **persist/restore**: real-shaped arrays in a CRC'd zip; the padded device
  layout is re-derived identically on load, so the AOT ``.aotbundle``
  sidecar stays valid and a cold process serves with zero compiles.

The index quacks enough like a model (``conf.to_json()``, ``dtype``,
``_aot_fns``) for ``nn/aot.py``'s bundle machinery to treat it as one.
"""

from __future__ import annotations

import io
import json
import os
import threading
import zlib
from dataclasses import asdict, dataclass, replace
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering, assign_points
from deeplearning4j_tpu.nn import aot
from deeplearning4j_tpu.search.program import SearchProgram
from deeplearning4j_tpu.utils import bucketing
from deeplearning4j_tpu.utils.serialization import _atomic_write_zip

__all__ = ["IndexConfig", "VectorIndex"]

INDEX_FORMAT_VERSION = 1
_MANIFEST = "manifest.json"

_METRICS = ("euclidean", "cosine")
TIERS = ("exact", "ivf", "ivf_pq")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")


@dataclass(frozen=True)
class IndexConfig:
    """Build-time configuration. The ``ivf_nlist`` / ``ivf_nprobe`` /
    ``search_batch_max`` knobs (tune/knobs.py, scope=serve) act here through
    their env variables when the corresponding field is left at its
    0/None sentinel — knobs act at BUILD time: a tuner trial rebuilds the
    index in its fresh subprocess, it cannot re-shape a live one."""

    dim: int
    name: str = "default"
    metric: str = "euclidean"          # "euclidean" | "cosine"
    ivf: bool = True                   # train the IVF tier at build
    nlist: int = 0                     # 0 = env DL4J_TPU_IVF_NLIST, else auto
    nprobe: int = 0                    # 0 = env DL4J_TPU_IVF_NPROBE, else 8
    pq_m: int = 0                      # subquantizers; 0 = PQ tier off
    pq_ksub: int = 256                 # codewords per subquantizer (<= 256)
    rerank: int = 64                   # PQ exact-rerank candidate width
    max_k: int = 16                    # largest k a request may ask for
    batch_max: int = 0                 # 0 = env DL4J_TPU_SEARCH_BATCH_MAX, else 32
    pending_cap: int = 1024            # incremental-add buffer rows; 0 = off
    train_sample: int = 20000          # centroid-training subsample cap
    kmeans_iters: int = 8
    seed: int = 12345
    k_choices: Optional[Tuple[int, ...]] = None       # override the k grid
    nprobe_choices: Optional[Tuple[int, ...]] = None  # override the probe grid

    def __post_init__(self):
        if self.metric not in _METRICS:
            raise ValueError(f"metric must be one of {_METRICS}, "
                             f"got {self.metric!r}")
        if self.pq_m and self.dim % self.pq_m:
            raise ValueError(
                f"pq_m={self.pq_m} must divide dim={self.dim}")
        if self.pq_ksub > 256:
            raise ValueError("pq_ksub > 256 does not fit uint8 codes")


class _Conf:
    """Minimal ``model.conf`` stand-in: ``aot.model_signature`` hashes
    ``conf.to_json()``, so the JSON carries the config plus every derived
    device shape — two indexes with different layouts never share a
    bundle."""

    def __init__(self, d: Dict):
        self._d = d

    def to_json(self) -> str:
        return json.dumps(self._d, sort_keys=True)


class VectorIndex:
    """Build with :meth:`build`, restore with :meth:`load`; then
    :meth:`search` / :meth:`add` / :meth:`save`."""

    def __init__(self, config: IndexConfig):
        self.config = config
        self.dtype = "float32"
        self.n = 0
        self._vectors = np.zeros((0, config.dim), np.float32)  # host copy
        self._corpus = None            # [capacity, D] device
        self._cnorms = None            # [capacity]
        self._centroids = None         # [nlist, D] or None (no IVF)
        self._assign = None            # [n] host list id per row
        self._postings = None          # [nlist, L] int32
        self._sizes = None             # [nlist] int32
        self._codes = None             # [capacity, M] uint8 or None
        self._codebooks = None         # [M, ksub, dsub]
        self._pending_np = None        # [pending_bucket, D] host
        self._pending_corpus = None    # device mirror
        self._pending_cnorms = None
        self._pending_n = 0
        self._lock = threading.RLock()
        self.stats: Dict = {}
        self.program = SearchProgram(self)

    # ------------------------------------------------------------------
    # build / load
    # ------------------------------------------------------------------

    @classmethod
    def build(cls, vectors, config: IndexConfig) -> "VectorIndex":
        """Train + lay out the index for ``vectors`` ([n, dim])."""
        vectors = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if vectors.ndim != 2 or vectors.shape[1] != config.dim:
            raise ValueError(
                f"vectors must be [n, {config.dim}], got {vectors.shape}")
        n = vectors.shape[0]
        if n < 1:
            raise ValueError("cannot build an empty index")
        config = cls._resolve_config(config, n)
        ix = cls(config)
        if config.metric == "cosine":
            vectors = _l2_normalize(vectors)
        centroids = codebooks = None
        assign = np.zeros(n, np.int32)
        codes = None
        rs = np.random.RandomState(config.seed)
        if config.ivf and config.nlist > 1:
            sample = _subsample(vectors, config.train_sample, rs)
            km = KMeansClustering(
                config.nlist, config.kmeans_iters, "euclidean",
                seed=config.seed, init="random")
            centroids = km.apply_to(sample).centers.astype(np.float32)
            assign, _ = assign_points(vectors, centroids)
            if config.pq_m:
                codebooks, codes = _train_pq(vectors, sample, config, rs)
        ix._install(vectors, centroids, assign, codebooks, codes)
        ix._measure_recall()
        obs.event("search_index_built", index=config.name, points=n,
                  nlist=int(config.nlist if centroids is not None else 0),
                  tier=ix.default_tier, **{"dim": config.dim})
        return ix

    @staticmethod
    def _resolve_config(config: IndexConfig, n: int) -> IndexConfig:
        """Fill the env/auto sentinels with concrete values for corpus size
        ``n`` (this resolved config is what the signature hashes)."""
        ladder = bucketing.ladder_from_env()
        batch_max = config.batch_max or _env_int(
            "DL4J_TPU_SEARCH_BATCH_MAX", 32)
        nprobe = config.nprobe or _env_int("DL4J_TPU_IVF_NPROBE", 8)
        nlist = config.nlist or _env_int("DL4J_TPU_IVF_NLIST", 0)
        if config.ivf and nlist == 0:
            # auto: ~sqrt(n) lists rounded up the ladder, capped so the
            # average list keeps enough occupants to be worth probing
            nlist = min(ladder.bucket(max(int(np.ceil(np.sqrt(n))), 1)),
                        max(n // 8, 1))
        nlist = min(nlist, n)
        nprobe = max(1, min(nprobe, max(nlist, 1)))
        return replace(config, batch_max=int(batch_max), nlist=int(nlist),
                       nprobe=int(nprobe))

    def _install(self, vectors, centroids, assign, codebooks, codes):
        """Derive the padded device layout from real-shaped host arrays.
        Deterministic in its inputs: build and cold load produce identical
        shapes, which is what keeps the .aotbundle sidecar valid."""
        cfg = self.config
        ladder = bucketing.ladder_from_env()
        n = vectors.shape[0]
        capacity = ladder.bucket(max(n, 1))
        self.n = n
        self._vectors = vectors
        corpus = np.zeros((capacity, cfg.dim), np.float32)
        corpus[:n] = vectors
        self._corpus = jnp.asarray(corpus)
        self._cnorms = jnp.asarray(np.sum(corpus * corpus, axis=1))
        if centroids is not None:
            nlist = centroids.shape[0]
            counts = np.bincount(assign, minlength=nlist)
            L = ladder.bucket(max(int(counts.max()), 1))
            postings = np.zeros((nlist, L), np.int32)
            sizes = counts.astype(np.int32)
            order = np.argsort(assign, kind="stable")
            off = 0
            for c in range(nlist):
                postings[c, :counts[c]] = order[off:off + counts[c]]
                off += counts[c]
            self._centroids = jnp.asarray(centroids)
            self._assign = np.asarray(assign, np.int32)
            self._postings = jnp.asarray(postings)
            self._sizes = jnp.asarray(sizes)
        else:
            self._centroids = self._postings = self._sizes = None
            self._assign = None
        if codes is not None:
            padded = np.zeros((capacity, codes.shape[1]), np.uint8)
            padded[:n] = codes
            self._codes = jnp.asarray(padded)
            self._codebooks = jnp.asarray(codebooks)
        else:
            self._codes = self._codebooks = None
        if cfg.pending_cap > 0:
            pcap = ladder.bucket(cfg.pending_cap)
            self._pending_np = np.zeros((pcap, cfg.dim), np.float32)
            self._pending_corpus = jnp.asarray(self._pending_np)
            self._pending_cnorms = jnp.zeros((pcap,), jnp.float32)
        self._pending_n = 0
        self.stats.update({
            "points": n, "capacity": int(capacity),
            "nlist": 0 if centroids is None else int(centroids.shape[0]),
            "tier": self.default_tier, "metric": cfg.metric,
        })

    # -- the model-shaped surface aot.py expects ---------------------------

    @property
    def conf(self) -> _Conf:
        cfg = asdict(self.config)
        cfg["k_choices"] = list(self.k_choices)
        cfg["nprobe_choices"] = list(self.nprobe_choices)
        derived = {
            "capacity": 0 if self._corpus is None else int(self._corpus.shape[0]),
            "list_width": 0 if self._postings is None else int(self._postings.shape[1]),
            "nlist": 0 if self._centroids is None else int(self._centroids.shape[0]),
            "pq": None if self._codebooks is None else list(self._codebooks.shape),
            "pending": 0 if self._pending_corpus is None else int(
                self._pending_corpus.shape[0]),
        }
        return _Conf({"index": cfg, "derived": derived})

    # ------------------------------------------------------------------
    # grids
    # ------------------------------------------------------------------

    @property
    def k_choices(self) -> Tuple[int, ...]:
        if self.config.k_choices:
            return tuple(self.config.k_choices)
        cap = self._corpus.shape[0] if self._corpus is not None else self.config.max_k
        ks = [b for b in aot.reachable_buckets(self.config.max_k) if b <= cap]
        return tuple(ks) or (min(self.config.max_k, cap),)

    @property
    def nprobe_choices(self) -> Tuple[int, ...]:
        if self._centroids is None:
            return ()
        nlist = int(self._centroids.shape[0])
        if self.config.nprobe_choices:
            return tuple(min(p, nlist) for p in self.config.nprobe_choices)
        return (min(self.config.nprobe, nlist),)

    def rerank_width(self, k: int) -> int:
        cap = int(self._corpus.shape[0])
        return min(max(self.config.rerank, k), cap)

    @property
    def default_tier(self) -> str:
        if self._codes is not None:
            return "ivf_pq"
        if self._centroids is not None:
            return "ivf"
        return "exact"

    def available_tiers(self) -> Tuple[str, ...]:
        out = ["exact"]
        if self._centroids is not None:
            out.append("ivf")
        if self._codes is not None:
            out.append("ivf_pq")
        return tuple(out)

    def warm(self) -> int:
        """AOT-compile every reachable request signature (delegates to the
        program; the registry calls this at register time)."""
        return self.program.warm()

    # ------------------------------------------------------------------
    # search
    # ------------------------------------------------------------------

    def search(self, queries, k: int = 10, nprobe: Optional[int] = None,
               tier: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``k`` ids + distances for ``queries`` ([B, dim]).

        Returns ``(ids, distances)`` as [B, k] host arrays; empty slots
        (k > live points) carry id -1 and distance +inf. Oversized batches
        are host-looped in ``batch_max`` slices; each slice pads up the
        shared ladder onto an AOT-warmed signature."""
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != self.config.dim:
            raise ValueError(
                f"queries must be [B, {self.config.dim}], got "
                f"{np.asarray(queries).shape}")
        if not 1 <= k <= self.config.max_k:
            raise ValueError(
                f"k must be in [1, {self.config.max_k}], got {k}")
        tier = tier or self.default_tier
        if tier not in self.available_tiers():
            raise ValueError(
                f"tier {tier!r} not available; index has "
                f"{self.available_tiers()}")
        if self.config.metric == "cosine":
            q = _l2_normalize(q)
        kb = min((c for c in self.k_choices if c >= k),
                 default=self.k_choices[-1])
        p = self._resolve_nprobe(nprobe) if tier != "exact" else 0
        ids_out, dist_out = [], []
        bm = self.config.batch_max
        with self._lock:
            for lo in range(0, q.shape[0], bm):
                ids, dists = self._search_slice(q[lo:lo + bm], kb, p, tier)
                ids_out.append(ids[:, :k])
                dist_out.append(dists[:, :k])
        obs.counter(
            "dl4j_search_requests_total",
            "search dispatches by index and scoring tier",
            ("index", "tier")).inc(index=self.config.name, tier=tier)
        return np.concatenate(ids_out), np.concatenate(dist_out)

    def _resolve_nprobe(self, nprobe: Optional[int]) -> int:
        choices = self.nprobe_choices
        if nprobe is None:
            return choices[0]
        # round up into the warmed grid (never out of it)
        return min((c for c in choices if c >= nprobe), default=choices[-1])

    def _search_slice(self, q: np.ndarray, kb: int, p: int, tier: str):
        rows = q.shape[0]
        b = bucketing.bucket_size(rows) if bucketing.bucketing_enabled() else rows
        tel = bucketing.telemetry()
        qd = jnp.asarray(bucketing.pad_rows_zero(q, b))
        nv = jnp.int32(self.n)
        zero = jnp.int32(0)
        if tier == "exact":
            tel.record_hit("search.exact", rows, b)
            scores, ids = self.program.exact(
                qd, self._corpus, self._cnorms, nv, zero, kb)
            scanned = np.full(rows, self.n, np.int64)
        elif tier == "ivf":
            tel.record_hit("search.ivf", rows, b)
            scores, ids, cnt = self.program.ivf(
                qd, self._centroids, self._postings, self._sizes,
                self._corpus, self._cnorms, p, kb)
            scanned = np.asarray(cnt[:rows], np.int64)
        else:
            tel.record_hit("search.ivf_pq", rows, b)
            scores, ids, cnt = self.program.pq(
                qd, self._centroids, self._postings, self._sizes,
                self._codes, self._codebooks, self._corpus, self._cnorms,
                p, kb, self.rerank_width(kb))
            scanned = np.asarray(cnt[:rows], np.int64)
        if self._pending_n > 0:
            tel.record_hit("search.exact", rows, b)
            ps, pi = self.program.exact(
                qd, self._pending_corpus, self._pending_cnorms,
                jnp.int32(self._pending_n), nv, kb)
            scores, ids = self.program.merge(scores, ids, ps, pi, kb)
            scanned = scanned + self._pending_n
        hist = obs.histogram(
            "dl4j_search_candidates_scanned",
            "candidates exactly/ADC-scored per query by tier",
            ("index", "tier"))
        for c in scanned:
            hist.observe(float(c), index=self.config.name, tier=tier)
        s = np.asarray(scores[:rows])
        i = np.asarray(ids[:rows])
        dead = ~np.isfinite(s)
        i = np.where(dead, -1, i)
        if self.config.metric == "cosine":
            d = np.where(dead, np.inf, np.maximum(-s, 0.0) / 2.0)
        else:
            d = np.where(dead, np.inf, np.sqrt(np.maximum(-s, 0.0)))
        return i, d.astype(np.float32)

    # ------------------------------------------------------------------
    # incremental adds
    # ------------------------------------------------------------------

    def add(self, vectors) -> np.ndarray:
        """Append rows; returns their ids. New rows live in the pending
        buffer (searchable immediately through the exact+merge pair) until
        ``merge_pending`` folds them into the main structure. A full buffer
        forces a synchronous merge — the backpressure is deliberate."""
        if self._pending_np is None:
            raise ValueError("index built with pending_cap=0: read-only")
        v = np.atleast_2d(np.asarray(vectors, np.float32))
        if v.shape[1] != self.config.dim:
            raise ValueError(f"vectors must be [*, {self.config.dim}]")
        if self.config.metric == "cosine":
            v = _l2_normalize(v)
        with self._lock:
            ids = []
            for row in v:
                if self._pending_n >= self.config.pending_cap:
                    self.merge_pending()
                self._pending_np[self._pending_n] = row
                ids.append(self.n + self._pending_n)
                self._pending_n += 1
            self._pending_corpus = jnp.asarray(self._pending_np)
            self._pending_cnorms = jnp.asarray(
                np.sum(self._pending_np * self._pending_np, axis=1))
        return np.asarray(ids, np.int64)

    def merge_pending(self) -> int:
        """Fold the pending buffer into the main structure (admin path:
        capacity/list-width may grow a rung, which compiles — never on the
        request path). Ids are stable: pending row i keeps id n+i. The
        coarse quantizer is NOT retrained; new rows join their nearest
        existing list (rebuild the index to re-center after heavy drift)."""
        with self._lock:
            if self._pending_n == 0:
                return 0
            merged = np.concatenate(
                [self._vectors, self._pending_np[:self._pending_n]])
            moved = self._pending_n
            centroids = (None if self._centroids is None
                         else np.asarray(self._centroids))
            assign = codes = codebooks = None
            if centroids is not None:
                new_assign, _ = assign_points(
                    self._pending_np[:moved], centroids)
                assign = np.concatenate([self._assign, new_assign])
                if self._codebooks is not None:
                    codebooks = np.asarray(self._codebooks)
                    old_codes = np.asarray(self._codes[:self.n])
                    new_codes = _encode_pq(
                        self._pending_np[:moved], codebooks)
                    codes = np.concatenate([old_codes, new_codes])
            old_shapes = (self._corpus.shape,
                          None if self._postings is None
                          else self._postings.shape)
            self._install(merged, centroids, assign, codebooks, codes)
            new_shapes = (self._corpus.shape,
                          None if self._postings is None
                          else self._postings.shape)
            if new_shapes != old_shapes:
                # grown a rung: re-warm so the request path stays compile-free
                self.program.warm()
            obs.event("search_pending_merged", index=self.config.name,
                      moved=moved, points=self.n,
                      grew=bool(new_shapes != old_shapes))
            return moved

    # ------------------------------------------------------------------
    # recall probe
    # ------------------------------------------------------------------

    def _measure_recall(self, k: int = 10, probes: int = 64):
        """Held-out probe set sampled at build time: corpus rows + small
        deterministic noise, recall@k of each ANN tier vs the exact tier.
        Feeds the dl4j_search_recall_at_k gauge and ``stats``."""
        k = min(k, self.config.max_k, self.n)
        if k < 1 or self.n < 2:
            return
        rs = np.random.RandomState(self.config.seed + 1)
        m = min(probes, self.n)
        base = self._vectors[rs.choice(self.n, size=m, replace=False)]
        scale = float(np.std(base)) or 1.0
        queries = base + rs.normal(0, 0.05 * scale, base.shape).astype(np.float32)
        exact_ids, _ = self.search(queries, k=k, tier="exact")
        gauge = obs.gauge(
            "dl4j_search_recall_at_k",
            "build-time recall vs the exact tier on a held-out probe set",
            ("index", "tier"))
        self.stats["recall_k"] = k
        for tier in self.available_tiers()[1:]:
            ids, _ = self.search(queries, k=k, tier=tier)
            hits = sum(len(np.intersect1d(a[a >= 0], b[b >= 0]))
                       for a, b in zip(exact_ids, ids))
            recall = hits / float(exact_ids.shape[0] * k)
            gauge.set(recall, index=self.config.name, tier=tier)
            self.stats[f"recall_at_{k}_{tier}"] = round(recall, 4)

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def save(self, path) -> str:
        """Real-shaped arrays + manifest in a CRC'd zip (atomic write).
        Merge the pending buffer first so nothing is lost."""
        with self._lock:
            if self._pending_n:
                self.merge_pending()
            arrays = {"vectors": self._vectors}
            if self._centroids is not None:
                arrays["centroids"] = np.asarray(self._centroids)
                arrays["assign"] = self._assign
            if self._codebooks is not None:
                arrays["codebooks"] = np.asarray(self._codebooks)
                arrays["codes"] = np.asarray(self._codes[:self.n])
            blobs = {}
            for name, arr in arrays.items():
                buf = io.BytesIO()
                np.save(buf, arr)
                blobs[f"{name}.npy"] = buf.getvalue()
            manifest = {
                "format_version": INDEX_FORMAT_VERSION,
                "config": asdict(self.config),
                "points": self.n,
                "stats": self.stats,
                "entries": {name: {"crc32": zlib.crc32(b) & 0xFFFFFFFF,
                                   "size": len(b)}
                            for name, b in blobs.items()},
            }

            def write_entries(zf):
                zf.writestr(_MANIFEST, json.dumps(manifest, indent=2))
                for name, b in blobs.items():
                    zf.writestr(name, b)

            _atomic_write_zip(path, write_entries)
            obs.event("search_index_saved", index=self.config.name,
                      path=str(path), points=self.n)
            return str(path)

    @classmethod
    def load(cls, path) -> "VectorIndex":
        """Rebuild the device layout from a saved index — no retraining,
        no re-assignment: derived shapes match the build exactly, so a
        bundle restored from ``aot.bundle_path_for(path)`` dispatches warm."""
        import zipfile

        with zipfile.ZipFile(path) as zf:
            manifest = json.loads(zf.read(_MANIFEST))
            if manifest.get("format_version") != INDEX_FORMAT_VERSION:
                raise ValueError(
                    f"index format {manifest.get('format_version')} != "
                    f"{INDEX_FORMAT_VERSION}")
            blobs = {}
            for name, meta in manifest["entries"].items():
                b = zf.read(name)
                if (zlib.crc32(b) & 0xFFFFFFFF) != meta["crc32"]:
                    raise ValueError(f"index entry {name} failed CRC")
                blobs[name] = np.load(io.BytesIO(b))
        cfg_d = manifest["config"]
        for key in ("k_choices", "nprobe_choices"):
            if cfg_d.get(key) is not None:
                cfg_d[key] = tuple(cfg_d[key])
        config = IndexConfig(**cfg_d)
        ix = cls(config)
        ix._install(
            np.asarray(blobs["vectors.npy"], np.float32),
            None if "centroids.npy" not in blobs else blobs["centroids.npy"],
            None if "assign.npy" not in blobs else blobs["assign.npy"],
            None if "codebooks.npy" not in blobs else blobs["codebooks.npy"],
            None if "codes.npy" not in blobs else blobs["codes.npy"],
        )
        for key, val in manifest.get("stats", {}).items():
            ix.stats.setdefault(key, val)
        obs.event("search_index_loaded", index=config.name, path=str(path),
                  points=ix.n)
        return ix


# ---------------------------------------------------------------------------
# build helpers
# ---------------------------------------------------------------------------


def _l2_normalize(v: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(v, axis=1, keepdims=True)
    return (v / np.maximum(norms, 1e-12)).astype(np.float32)


def _subsample(vectors: np.ndarray, cap: int,
               rs: np.random.RandomState) -> np.ndarray:
    if vectors.shape[0] <= cap:
        return vectors
    return vectors[rs.choice(vectors.shape[0], size=cap, replace=False)]


def _train_pq(vectors, sample, config: IndexConfig, rs):
    """Per-subspace codebooks (random-init Lloyd on the training sample)
    and uint8 codes for the full corpus, encoded through the bucketed
    ``kmeans.assign`` site."""
    m, ksub = config.pq_m, config.pq_ksub
    dsub = config.dim // m
    ksub_eff = min(ksub, sample.shape[0])
    books = np.zeros((m, ksub, dsub), np.float32)
    codes = np.zeros((vectors.shape[0], m), np.uint8)
    for j in range(m):
        sub = np.ascontiguousarray(sample[:, j * dsub:(j + 1) * dsub])
        km = KMeansClustering(ksub_eff, config.kmeans_iters, "euclidean",
                              seed=config.seed + 7 * j + 1, init="random")
        centers = km.apply_to(sub).centers.astype(np.float32)
        books[j, :ksub_eff] = centers
        if ksub_eff < ksub:           # unused codebook slots: never encoded
            books[j, ksub_eff:] = centers[0]
        full_sub = np.ascontiguousarray(
            vectors[:, j * dsub:(j + 1) * dsub])
        a, _ = assign_points(full_sub, centers)
        codes[:, j] = a.astype(np.uint8)
    return books, codes


def _encode_pq(vectors: np.ndarray, codebooks: np.ndarray) -> np.ndarray:
    m, _, dsub = codebooks.shape
    codes = np.zeros((vectors.shape[0], m), np.uint8)
    for j in range(m):
        sub = np.ascontiguousarray(vectors[:, j * dsub:(j + 1) * dsub])
        a, _ = assign_points(sub, codebooks[j])
        codes[:, j] = a.astype(np.uint8)
    return codes
