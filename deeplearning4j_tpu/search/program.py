"""Compiled kernels of the device-resident search tiers.

Three scoring tiers share one compiled-kernel discipline (docs/SEARCH.md):

- **exact**  — brute-force matmul top-k over a fixed-capacity corpus chunk.
  The squared-euclidean expansion ``||q||² - 2 q·x + ||x||²`` puts the whole
  scan on the MXU as a single [B, C] matmul; padded corpus rows are masked
  to -inf before ``lax.top_k``.
- **ivf**    — coarse-quantizer assign (a tiny [B, nlist] matmul against the
  k-means centroids) picks ``nprobe`` inverted lists per query, then a
  ``lax.scan`` over the probed lists gathers each list's vectors and exact-
  scores them, carrying a running top-k. Work drops from O(C) to
  O(nprobe · L) per query.
- **ivf_pq** — same probe loop, but candidates are scored from uint8 PQ
  codes via an ADC lookup table (``lut[b, m, code]`` built once per batch),
  carrying a top-``r`` candidate set that a final exact gather reranks down
  to k. Memory touched per candidate falls from D floats to M bytes.

Every body is built through :class:`nn.step_program.StepProgram` (the
step-wiring rule: no raw ``jit(donate_argnums)``), records its compile via
``bucketing.record_trace`` from inside the traced body, and takes its batch
already padded onto the shared bucket ladder — so the reachable signature
grid is finite and :meth:`SearchProgram.warm` can AOT-compile all of it
before the first request (zero request-path compiles, the same contract the
model-serving tier holds).

Score convention: **scores are negated squared-euclidean distances**
throughout (larger = closer), so ``lax.top_k`` works unmodified and invalid
slots are -inf. Cosine similarity is served by L2-normalizing corpus and
queries at build/search time (monotone-equivalent ordering); the host layer
converts final scores back to user-facing distances.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn import aot
from deeplearning4j_tpu.nn.step_program import StepProgram
from deeplearning4j_tpu.utils import bucketing

__all__ = [
    "SITE_EXACT", "SITE_IVF", "SITE_MERGE", "SITE_PQ", "SearchProgram",
]

SITE_EXACT = "search.exact"
SITE_MERGE = "search.merge"
SITE_IVF = "search.ivf"
SITE_PQ = "search.ivf_pq"


def _exact_body(q, chunk, cnorms, n_valid, offset, k):
    """[B, C] exact scores + top-k. ``n_valid`` (dynamic scalar) masks the
    capacity padding; ids past it can never surface. ``offset`` shifts the
    returned ids into the global id space (the pending buffer scores at
    offset = main-corpus count so its hits merge correctly)."""
    bucketing.telemetry().record_trace(
        SITE_EXACT, (q.shape[0], chunk.shape[0], k))
    qn = jnp.sum(q * q, axis=-1)
    d = qn[:, None] - 2.0 * (q @ chunk.T) + cnorms[None, :]
    col_ok = jnp.arange(chunk.shape[0]) < n_valid
    scores = jnp.where(col_ok[None, :], -d, -jnp.inf)
    best, idx = jax.lax.top_k(scores, k)
    return best, idx.astype(jnp.int32) + offset


def _merge_body(sa, ia, sb, ib, k):
    """Merge two per-query top-k result sets (main corpus + pending buffer)
    into one, preserving global id spaces carried in ``ia``/``ib``."""
    bucketing.telemetry().record_trace(
        SITE_MERGE, (sa.shape[0], sa.shape[1] + sb.shape[1], k))
    s = jnp.concatenate([sa, sb], axis=1)
    i = jnp.concatenate([ia, ib], axis=1)
    best, sel = jax.lax.top_k(s, k)
    return best, jnp.take_along_axis(i, sel, axis=1)


def _ivf_body(q, centroids, postings, sizes, corpus, cnorms, nprobe, k):
    """IVF probe loop: coarse top-nprobe lists, then a scan over the probed
    lists carrying a running exact top-k. Returns ``(scores, ids, counts)``
    where counts is candidates actually scored per query (the
    dl4j_search_candidates_scanned histogram source)."""
    B = q.shape[0]
    L = postings.shape[1]
    bucketing.telemetry().record_trace(SITE_IVF, (B, nprobe, k))
    qn = jnp.sum(q * q, axis=-1)
    centnorms = jnp.sum(centroids * centroids, axis=-1)
    dc = qn[:, None] - 2.0 * (q @ centroids.T) + centnorms[None, :]
    _, probe = jax.lax.top_k(-dc, nprobe)                      # [B, nprobe]

    def step(carry, pid):                                      # pid: [B]
        best, bidx, cnt = carry
        rows = postings[pid]                                   # [B, L]
        valid = jnp.arange(L)[None, :] < sizes[pid][:, None]
        vecs = corpus[rows]                                    # [B, L, D]
        dot = jnp.einsum("bd,bld->bl", q, vecs)
        d = qn[:, None] - 2.0 * dot + cnorms[rows]
        sc = jnp.where(valid, -d, -jnp.inf)
        nb, sel = jax.lax.top_k(jnp.concatenate([best, sc], axis=1), k)
        ni = jnp.take_along_axis(
            jnp.concatenate([bidx, rows], axis=1), sel, axis=1)
        return (nb, ni, cnt + jnp.sum(valid, axis=1)), None

    init = (jnp.full((B, k), -jnp.inf, q.dtype),
            jnp.full((B, k), -1, jnp.int32),
            jnp.zeros((B,), jnp.int32))
    (best, ids, cnt), _ = jax.lax.scan(step, init, probe.T)
    return best, ids, cnt


def _pq_body(q, centroids, postings, sizes, codes, codebooks, corpus,
             cnorms, nprobe, k, r):
    """IVF-PQ: ADC-score candidates from uint8 codes (M bytes each, not D
    floats), carry a top-``r`` candidate set through the probe scan, then
    exact-rerank the r survivors down to k from the full-precision corpus."""
    B = q.shape[0]
    M, ksub, dsub = codebooks.shape
    L = postings.shape[1]
    bucketing.telemetry().record_trace(SITE_PQ, (B, nprobe, k, r))
    qn = jnp.sum(q * q, axis=-1)
    centnorms = jnp.sum(centroids * centroids, axis=-1)
    dc = qn[:, None] - 2.0 * (q @ centroids.T) + centnorms[None, :]
    _, probe = jax.lax.top_k(-dc, nprobe)
    # ADC table: lut[b, m, j] = ||q_m - codebook[m, j]||², one build per batch
    lut = jnp.sum(
        (q.reshape(B, M, 1, dsub) - codebooks[None]) ** 2, axis=-1)

    def step(carry, pid):
        best, bidx, cnt = carry
        rows = postings[pid]                                   # [B, L]
        valid = jnp.arange(L)[None, :] < sizes[pid][:, None]
        cg = codes[rows].astype(jnp.int32)                     # [B, L, M]
        adc = jnp.sum(
            jnp.take_along_axis(lut, cg.transpose(0, 2, 1), axis=2), axis=1)
        sc = jnp.where(valid, -adc, -jnp.inf)
        nb, sel = jax.lax.top_k(jnp.concatenate([best, sc], axis=1), r)
        ni = jnp.take_along_axis(
            jnp.concatenate([bidx, rows], axis=1), sel, axis=1)
        return (nb, ni, cnt + jnp.sum(valid, axis=1)), None

    init = (jnp.full((B, r), -jnp.inf, q.dtype),
            jnp.full((B, r), -1, jnp.int32),
            jnp.zeros((B,), jnp.int32))
    (approx, cand, cnt), _ = jax.lax.scan(step, init, probe.T)
    # exact rerank of the r ADC survivors (clip keeps the gather in bounds
    # for empty -1 slots; their -inf approx score masks them back out)
    safe = jnp.clip(cand, 0, corpus.shape[0] - 1)
    vecs = corpus[safe]                                        # [B, r, D]
    dot = jnp.einsum("bd,brd->br", q, vecs)
    d = qn[:, None] - 2.0 * dot + cnorms[safe]
    sc = jnp.where(jnp.isfinite(approx), -d, -jnp.inf)
    best, sel = jax.lax.top_k(sc, k)
    return best, jnp.take_along_axis(cand, sel, axis=1), cnt


class SearchProgram:
    """The four compiled sites of one :class:`search.index.VectorIndex`,
    registered on the index's AOT registry (``model=index``) so bundle
    save/restore and ladder warmup find them exactly like model steps.

    Nothing is donated: the corpus/centroid/posting arrays are the index's
    long-lived device state, reused by every dispatch.
    """

    def __init__(self, index):
        self.index = index
        sp = lambda body, site, statics: StepProgram(
            body, site, model=index, donate_argnums=(),
            static_argnums=statics)
        self.exact = sp(_exact_body, SITE_EXACT, (5,))
        self.merge = sp(_merge_body, SITE_MERGE, (4,))
        self.ivf = sp(_ivf_body, SITE_IVF, (6, 7))
        self.pq = sp(_pq_body, SITE_PQ, (8, 9, 10))

    # -- warmup ------------------------------------------------------------

    def signature_grid(self) -> List[Tuple[int, int, int]]:
        """Every (B, k, nprobe) combination a request can dispatch at: B and
        k walk the reachable rungs of the shared ladder up to the index's
        caps, nprobe comes from the index's (small) probe choice set. This
        grid is what ``warm()`` compiles and what keeps the request path at
        zero compiles — requests are padded/rounded INTO it, never out."""
        ix = self.index
        ladder = bucketing.ladder_from_env()
        bs = aot.reachable_buckets(ix.config.batch_max, ladder)
        ks = ix.k_choices
        ps = ix.nprobe_choices
        return [(b, k, p) for b in bs for k in ks for p in ps]

    def warm(self) -> int:
        """AOT-compile the full reachable grid for every tier this index
        has (exact always; ivf/pq when trained; the pending-merge pair when
        incremental adds are enabled). Idempotent; returns the number of
        executables now warm. Bundle-restored signatures are cache hits."""
        ix = self.index
        d = ix.config.dim
        dt = jnp.float32
        zero = jnp.int32(0)
        grid = self.signature_grid()
        for b, k, p in grid:
            q = jnp.zeros((b, d), dt)
            self.exact.warm(q, ix._corpus, ix._cnorms, zero, zero, k,
                            cost_key=f"b{b}k{k}")
            if ix._pending_corpus is not None:
                self.exact.warm(q, ix._pending_corpus, ix._pending_cnorms,
                                zero, zero, k, cost_key=f"pend_b{b}k{k}")
                sa = jnp.zeros((b, k), dt)
                ia = jnp.zeros((b, k), jnp.int32)
                self.merge.warm(sa, ia, sa, ia, k, cost_key=f"b{b}k{k}")
            if ix._centroids is not None:
                self.ivf.warm(q, ix._centroids, ix._postings, ix._sizes,
                              ix._corpus, ix._cnorms, p, k,
                              cost_key=f"b{b}k{k}p{p}")
            if ix._codes is not None:
                self.pq.warm(q, ix._centroids, ix._postings, ix._sizes,
                             ix._codes, ix._codebooks, ix._corpus,
                             ix._cnorms, p, k, ix.rerank_width(k),
                             cost_key=f"b{b}k{k}p{p}")
        n = sum(fn.compiled_count
                for fn in (self.exact, self.merge, self.ivf, self.pq))
        obs.event("search_warm", index=ix.config.name, grid=len(grid),
                  executables=n)
        return n

    def compiled_count(self) -> int:
        return sum(fn.compiled_count
                   for fn in (self.exact, self.merge, self.ivf, self.pq))

    def compiles_observed(self) -> int:
        """Total traces recorded against the search sites (the request-path
        compile gate reads the delta of this across a serving window)."""
        tel = bucketing.telemetry()
        return sum(tel.compiles(s)
                   for s in (SITE_EXACT, SITE_MERGE, SITE_IVF, SITE_PQ))
