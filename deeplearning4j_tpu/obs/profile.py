"""Static XLA cost models + roofline utilization (MFU / memory bandwidth).

This module turns the executables the process already produces into a cost
ledger nobody has to pay twice for:

- **AOT harvest** — every ``Compiled`` the ``nn/aot.py`` dispatcher holds is
  passed to :func:`harvest_compiled` right after compilation:
  ``cost_analysis()`` (flops / bytes accessed / transcendentals) plus
  ``memory_analysis()`` (argument/output/temp/code bytes, summed into a
  peak-HBM estimate) land in registry gauges and the in-process ledger.
- **Lazy harvest** — sites that compile through the ordinary ``jit`` path
  can't hand us a ``Compiled``, but ``bucketing.record_trace`` (which runs
  exactly once per XLA compile, inside the traced body) calls
  :func:`note_trace`, flagging the site. After the dispatch returns, the
  ``AotFunction`` wrapper checks :func:`wants_exemplar` (one set lookup —
  the only hot-path cost of this module) and captures the call's *abstract*
  signature via :func:`note_exemplar`: ``shaped_abstractify`` avals plus a
  weakref to the dispatcher, never live buffers. Resolution is deferred to
  :func:`cost_report`: ``jit.lower(*avals)`` with the exact avals hits
  jax's jaxpr cache (no re-trace, no compile-counter pollution — verified
  against jax 0.4.37) and ``Lowered.cost_analysis()`` prices the HLO
  without compiling. Lazy entries have no ``memory_analysis`` (that needs a
  compile), so ``peak_hbm_bytes`` is reported only for AOT-warmed sites.
- **Roofline division** — achieved per-dispatch wall time comes from the
  ``dl4j_span_seconds`` histograms (p50 of the span mapped to each site);
  dividing harvested flops / bytes-accessed by it and by the per-backend
  peak table yields ``dl4j_mfu{site}`` and ``dl4j_membw_util{site}``. The
  peak table absorbs the ad-hoc math previously duplicated in ``bench.py``
  and ``tools/exp_transformer_mfu.py``; ``DL4J_TPU_PEAK_FLOPS`` /
  ``DL4J_TPU_HBM_GBPS`` override it so CPU runs (tests, smoke) can exercise
  the full pipeline.

Hot-path discipline: :func:`note_trace` / :func:`wants_exemplar` are a set
add / set lookup with no jax import; everything that touches jax
(:func:`harvest_compiled`, resolution, :func:`utilization`) runs at
compile time or report time — never per batch. The ``graftlint`` rule
``cost-analysis-off-hot-path`` enforces the same boundary statically.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.obs import metrics

__all__ = [
    "cost_report",
    "harvest_compiled",
    "note_exemplar",
    "note_trace",
    "peak_flops",
    "reset",
    "roofline",
    "snapshot",
    "utilization",
    "wants_exemplar",
]

# Per-chip peaks by device_kind substring: (bf16 FLOP/s, f32 FLOP/s,
# HBM bytes/s). FLOP columns match the table bench.py carried since PR 3
# (public TPU spec sheets); HBM column from the same sheets. First
# substring match wins; CPU / unknown kinds return None so utilization is
# omitted rather than fabricated (unless the env overrides below are set).
ROOFLINES: Tuple[Tuple[str, float, float, float], ...] = (
    ("v6", 918e12, 459e12, 1640e9),
    ("v5p", 459e12, 459e12, 2765e9),
    ("v5 lite", 197e12, 98e12, 819e9),
    ("v5e", 197e12, 98e12, 819e9),
    ("v4", 275e12, 137e12, 1228e9),
    ("v3", 123e12, 61e12, 900e9),
    ("v2", 45e12, 22e12, 700e9),
)

# Which span's per-dispatch wall time prices each harvested site. fit spans
# wrap exactly one step dispatch; output spans wrap one forward dispatch.
_SITE_SPANS = {
    "mln.step": "mln.fit_batch",
    "mln.step.tbptt": "mln.fit_batch",
    "mln.chain": "mln.fit_batch",  # one fit_batch span per chain dispatch
    "cg.step": "cg.fit_batch",
    "cg.step.tbptt": "cg.fit_batch",
    "dp.step": "dp.step",
    "mln.output": "mln.output",
    "cg.output": "cg.output",
}

_lock = threading.Lock()
# (site, key) -> cost entry dict (see harvest_compiled / _resolve_pending)
_costs: Dict[Tuple[str, str], dict] = {}
# sites flagged by note_trace, cleared when an exemplar is captured
_want_exemplar: set = set()
# site -> {"ref": weakref-or-None, "fn": strong-ref-or-None, "abstract": tree}
# keyed by (site, aval-key) so re-compiles at new shapes get their own entry
_exemplars: Dict[Tuple[str, object], dict] = {}


def _gauges():
    reg = metrics.registry()
    return (
        reg.gauge("dl4j_xla_flops",
                  "XLA cost-model FLOPs of one dispatch of the compiled "
                  "executable", ("site", "key")),
        reg.gauge("dl4j_xla_bytes_accessed",
                  "XLA cost-model bytes accessed by one dispatch",
                  ("site", "key")),
        reg.gauge("dl4j_xla_peak_hbm_bytes",
                  "compiled-executable memory footprint: argument + output "
                  "+ temp + generated code bytes (AOT-warmed sites only)",
                  ("site", "key")),
    )


# ---------------------------------------------------------------------------
# Roofline table
# ---------------------------------------------------------------------------

def _device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def roofline(device_kind: Optional[str] = None) -> dict:
    """Peak numbers for the backend: ``{device_kind, peak_bf16_flops,
    peak_f32_flops, hbm_bytes_per_s, source}``. Peaks are None for CPU /
    unknown kinds unless ``DL4J_TPU_PEAK_FLOPS`` (FLOP/s) /
    ``DL4J_TPU_HBM_GBPS`` (GB/s) override them."""
    kind = device_kind if device_kind is not None else _device_kind()
    bf16 = f32 = hbm = None
    source = "unknown"
    low = kind.lower()
    for sub, peak_bf16, peak_f32, peak_hbm in ROOFLINES:
        if sub in low:
            bf16, f32, hbm = peak_bf16, peak_f32, peak_hbm
            source = "table"
            break
    env_flops = os.environ.get("DL4J_TPU_PEAK_FLOPS")
    if env_flops:
        try:
            bf16 = f32 = float(env_flops)
            source = "env"
        except ValueError:
            pass
    env_hbm = os.environ.get("DL4J_TPU_HBM_GBPS")
    if env_hbm:
        try:
            hbm = float(env_hbm) * 1e9
            source = "env"
        except ValueError:
            pass
    return {
        "device_kind": kind,
        "peak_bf16_flops": bf16,
        "peak_f32_flops": f32,
        "hbm_bytes_per_s": hbm,
        "source": source,
    }


def peak_flops(dtype: str = "bfloat16",
               device_kind: Optional[str] = None) -> Optional[float]:
    """Peak FLOP/s for the backend at the given matmul precision; None for
    CPU / unknown (callers omit MFU rather than fabricate it)."""
    r = roofline(device_kind)
    return r["peak_bf16_flops"] if dtype == "bfloat16" else r["peak_f32_flops"]


# ---------------------------------------------------------------------------
# Harvest: AOT path
# ---------------------------------------------------------------------------

def harvest_compiled(site: str, compiled, key: str, dtype: str = "") -> Optional[dict]:
    """Record the cost/memory analysis of a ``Compiled`` executable under
    (site, key). Called from ``nn/aot.py`` at warm/restore time — never on
    the dispatch path. Never raises (backends without cost analysis simply
    contribute no entry)."""
    try:
        ca = compiled.cost_analysis()  # graftlint: disable=cost-analysis-off-hot-path
        ca = ca[0] if isinstance(ca, list) else (ca or {})
    except Exception:
        ca = {}
    entry = {
        "source": "aot",
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
        "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0),
    }
    if dtype:
        entry["dtype"] = dtype
    try:
        ma = compiled.memory_analysis()  # graftlint: disable=cost-analysis-off-hot-path
    except Exception:
        ma = None
    if ma is not None:
        arg = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
        out = float(getattr(ma, "output_size_in_bytes", 0) or 0)
        tmp = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
        code = float(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
        alias = float(getattr(ma, "alias_size_in_bytes", 0) or 0)
        entry.update({
            "argument_bytes": arg,
            "output_bytes": out,
            "temp_bytes": tmp,
            "generated_code_bytes": code,
            "alias_bytes": alias,
            # what the executable needs resident at dispatch (aliased/donated
            # bytes are double-counted in argument+output, so subtract)
            "peak_hbm_bytes": max(0.0, arg + out + tmp + code - alias),
        })
    if not entry["flops"] and not entry["bytes_accessed"] and ma is None:
        return None  # backend exposes nothing — don't record an empty row
    with _lock:
        _costs[(site, str(key))] = entry
    _set_cost_gauges(site, str(key), entry)
    return entry


def _set_cost_gauges(site: str, key: str, entry: dict):
    g_flops, g_bytes, g_hbm = _gauges()
    if entry.get("flops"):
        g_flops.set(entry["flops"], site=site, key=key)
    if entry.get("bytes_accessed"):
        g_bytes.set(entry["bytes_accessed"], site=site, key=key)
    if entry.get("peak_hbm_bytes"):
        g_hbm.set(entry["peak_hbm_bytes"], site=site, key=key)


# ---------------------------------------------------------------------------
# Harvest: lazy-jit path
# ---------------------------------------------------------------------------

def note_trace(site: str, shape=None):
    """Flag ``site`` as having just compiled through the lazy jit path.
    Called from ``bucketing.record_trace`` inside the traced body — must
    stay jax-free and O(1). ``shape`` is accepted for symmetry but unused
    (the exemplar carries exact avals)."""
    with _lock:
        _want_exemplar.add(site)


def wants_exemplar(site: str) -> bool:
    """One set lookup; the only per-dispatch cost of the lazy harvest."""
    return site in _want_exemplar


def note_exemplar(site: str, fn, args, kwargs):
    """Capture the abstract signature of the dispatch that just compiled.

    ``fn`` is the ``AotFunction`` wrapper (``fn._jit`` is the jitted
    callable). Stores ``shaped_abstractify`` avals — shape/dtype/weak_type
    only, never live buffers — plus a weakref to ``fn`` so a collected
    model doesn't stay pinned. Never raises."""
    try:
        import jax

        abstract = jax.tree_util.tree_map(
            jax.api_util.shaped_abstractify, (tuple(args), dict(kwargs)))
        leaves, treedef = jax.tree_util.tree_flatten(abstract)
        akey = (treedef, tuple((a.shape, str(a.dtype), bool(getattr(a, "weak_type", False)))
                               for a in leaves))
        try:
            ref, strong = weakref.ref(fn), None
        except TypeError:
            ref, strong = None, fn
        with _lock:
            _exemplars[(site, akey)] = {
                "ref": ref, "fn": strong, "abstract": abstract}
            _want_exemplar.discard(site)
    except Exception:
        with _lock:
            _want_exemplar.discard(site)  # a capture that can't work: no retry


def _resolve_pending():
    """Price every captured exemplar via ``jit.lower(*avals)`` +
    ``Lowered.cost_analysis()``. The exact avals hit jax's jaxpr cache, so
    the traced body does NOT re-execute (no compile-counter pollution) and
    nothing is compiled. Resolved exemplars are dropped; failures are
    recorded once as error entries so they aren't retried every report."""
    with _lock:
        pending = dict(_exemplars)
        _exemplars.clear()
    for (site, akey), rec in pending.items():
        fn = rec["fn"] if rec["fn"] is not None else rec["ref"]()
        if fn is None:
            continue  # model was collected; nothing to price
        key = f"sig{abs(hash(akey)) % 10**8:08d}"
        try:
            args2, kwargs2 = rec["abstract"]
            # AotFunction wrappers carry the jitted callable on ._jit;
            # bare jax.jit objects (e.g. the chained fit executable) lower
            # directly
            lowered = getattr(fn, "_jit", fn).lower(*args2, **kwargs2)
            ca = lowered.cost_analysis()  # graftlint: disable=cost-analysis-off-hot-path
            ca = ca[0] if isinstance(ca, list) else (ca or {})
            entry = {
                "source": "lazy",
                "flops": float(ca.get("flops", 0.0) or 0.0),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
                "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0),
            }
        except Exception as e:  # pragma: no cover - backend-specific
            entry = {"source": "lazy", "error": type(e).__name__}
        with _lock:
            # an AOT harvest for the same site/shape is strictly richer
            # (adds memory_analysis) — don't clobber it with a lazy probe
            existing = [k for k in _costs if k[0] == site
                        and _costs[k]["source"] == "aot"]
            if not existing or "error" not in entry:
                _costs.setdefault((site, key), entry)
        if "error" not in entry:
            _set_cost_gauges(site, key, entry)


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------

def utilization(span_summary: Optional[Dict[str, dict]] = None) -> Dict[str, dict]:
    """MFU / memory-bandwidth utilization per harvested site.

    ``achieved = flops / p50_wall_per_dispatch``; MFU divides by the bf16
    roofline (jax's default TPU matmul precision multiplies f32 inputs in
    bf16 — same convention the LSTM bench used), bandwidth by HBM bytes/s.
    Uses the largest-flops entry per site (the biggest bucket dominates a
    saturated ladder). Refreshes ``dl4j_mfu`` / ``dl4j_membw_util`` gauges.
    Empty when the backend has no roofline and no env override."""
    r = roofline()
    peak = r["peak_bf16_flops"]
    hbm = r["hbm_bytes_per_s"]
    if not peak and not hbm:
        return {}
    if span_summary is None:
        from deeplearning4j_tpu.obs import spans

        span_summary = spans.tracer().summary()
    with _lock:
        by_site: Dict[str, dict] = {}
        for (site, key), entry in _costs.items():
            if entry.get("flops", 0) > by_site.get(site, {}).get("flops", -1):
                by_site[site] = {**entry, "key": key}
    reg = metrics.registry()
    g_mfu = reg.gauge("dl4j_mfu",
                      "model FLOPs utilization: achieved flops/s at the "
                      "site's step span over the bf16 roofline", ("site",))
    g_bw = reg.gauge("dl4j_membw_util",
                     "achieved bytes-accessed/s over peak HBM bandwidth",
                     ("site",))
    out: Dict[str, dict] = {}
    for site, entry in by_site.items():
        span = _SITE_SPANS.get(site, site)
        s = span_summary.get(span)
        if not s or not s.get("count") or not s.get("wall_p50_s"):
            continue
        wall = s["wall_p50_s"]
        u = {"span": span, "key": entry["key"], "wall_p50_s": wall,
             "source": entry["source"]}
        if peak and entry.get("flops"):
            u["achieved_flops_per_s"] = entry["flops"] / wall
            u["mfu"] = entry["flops"] / wall / peak
            g_mfu.set(round(u["mfu"], 6), site=site)
        if hbm and entry.get("bytes_accessed"):
            u["achieved_bytes_per_s"] = entry["bytes_accessed"] / wall
            u["membw_util"] = entry["bytes_accessed"] / wall / hbm
            g_bw.set(round(u["membw_util"], 6), site=site)
        if "mfu" in u or "membw_util" in u:
            out[site] = u
    return out


def cost_report(resolve: bool = True) -> dict:
    """The profiling ledger: roofline, per-(site, key) static costs, and
    derived utilization. ``resolve=True`` prices any pending lazy-compile
    exemplars first (report time, never the hot path)."""
    if resolve:
        _resolve_pending()
    with _lock:
        sites: Dict[str, dict] = {}
        for (site, key), entry in sorted(_costs.items()):
            sites.setdefault(site, {})[key] = dict(entry)
    return {
        "roofline": roofline(),
        "sites": sites,
        "utilization": utilization(),
    }


def snapshot(resolve: bool = True) -> dict:
    """JSON-friendly view for ``obs.snapshot()`` (bench results, checkpoint
    telemetry). Same shape as :func:`cost_report`."""
    try:
        return cost_report(resolve=resolve)
    except Exception:  # never let profiling break a checkpoint save
        return {"roofline": {"device_kind": "unknown", "source": "error"},
                "sites": {}, "utilization": {}}


def reset():
    """Drop the ledger and pending exemplars (tests / bench isolation)."""
    with _lock:
        _costs.clear()
        _exemplars.clear()
        _want_exemplar.clear()
