"""Serving SLO instrumentation: latency, saturation, and burn rate.

One :class:`SloTracker` per process watches every request path (HTTP routes
on ``ui/server.py``, the ``ParallelInference`` serving queue) and maintains,
per route:

- ``dl4j_request_seconds{route}``     — latency histogram whose P² streaming
  quantiles (p50/p95/p99, obs/metrics.py) stay accurate over the whole
  stream, not just a recent window. Each series also carries mergeable
  fixed-boundary bucket counts (``metrics.BUCKET_BOUNDS``), so the fleet
  collector (obs/fleet.py) can ADD counts across workers and compute a
  true federated p99 — quantiles themselves never merge;
- ``dl4j_requests_total{route,status}`` — request counter (``status`` is the
  HTTP status class or ``ok``/``error`` for non-HTTP paths);
- ``dl4j_slo_burn_rate{route}``       — how fast the route is spending its
  error budget over a sliding window: ``bad_fraction / (1 - objective)``.
  1.0 = burning budget exactly as fast as the objective allows; >1 = paging
  territory; 0 = clean window. A request is *bad* when it errors, its
  latency exceeds the threshold, or it was SHED by the serving tier;
- ``dl4j_shed_total{route,reason}``   — load-shedding decisions by reason
  (``backpressure`` → HTTP 429, ``deadline`` → HTTP 503; ``serve/``).
  Shed requests also count into ``dl4j_requests_total{status="shed"}`` and
  into the burn-rate window, so overload moves the same gauge paging
  watches for latency SLO violations.

Knobs (read at tracker construction): ``DL4J_TPU_SLO_LATENCY_MS`` (latency
threshold, default 250), ``DL4J_TPU_SLO_ROUTE_LATENCY_MS`` (per-route
overrides as comma-separated ``prefix=ms`` pairs, longest matching prefix
wins — e.g. ``search:http=50,generate=2000`` holds search to 50ms while
generation keeps a 2s envelope), ``DL4J_TPU_SLO_OBJECTIVE`` (good-request
objective, default 0.99), ``DL4J_TPU_SLO_WINDOW_S`` (sliding window,
default 300).

Gauges for saturation live next to the code that owns the resource:
``dl4j_serving_queue_depth`` / ``dl4j_serving_in_flight``
(``parallel/inference.py``) and ``dl4j_http_in_flight`` (``ui/server.py``).

Recording is host-side arithmetic on ``perf_counter`` scalars under a lock
— O(1) amortized per request (stale-window eviction is paid incrementally
by the requests that observe it). Rides the ``DL4J_TPU_OBS=0`` kill switch.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from deeplearning4j_tpu.obs import metrics

__all__ = ["SloTracker", "slo_tracker", "observe_request", "observe_shed",
           "observe_ttft", "observe_itl", "set_decode_occupancy"]


def _parse_route_thresholds(spec: str) -> Dict[str, float]:
    """``"search:http=50,generate=2000"`` -> {prefix: seconds}. Malformed
    pairs are skipped — a bad knob value must not take down the tracker."""
    out: Dict[str, float] = {}
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair or "=" not in pair:
            continue
        prefix, _, ms = pair.rpartition("=")
        try:
            out[prefix.strip()] = float(ms) / 1e3
        except ValueError:
            continue
    return out


class SloTracker:
    def __init__(self,
                 reg: Optional[metrics.MetricsRegistry] = None,
                 threshold_s: Optional[float] = None,
                 objective: Optional[float] = None,
                 window_s: Optional[float] = None):
        self._reg = reg or metrics.registry()
        env = os.environ.get
        if threshold_s is None:
            threshold_s = float(env("DL4J_TPU_SLO_LATENCY_MS", "250")) / 1e3
        if objective is None:
            objective = float(env("DL4J_TPU_SLO_OBJECTIVE", "0.99"))
        if window_s is None:
            window_s = float(env("DL4J_TPU_SLO_WINDOW_S", "300"))
        self.threshold_s = threshold_s
        self.route_thresholds_s = _parse_route_thresholds(
            env("DL4J_TPU_SLO_ROUTE_LATENCY_MS", ""))
        self.objective = min(max(objective, 0.0), 0.999999)
        self.window_s = window_s
        self._hist = self._reg.histogram(
            "dl4j_request_seconds",
            "request latency by route (P² streaming quantiles; serving SLO "
            "source of truth)", ("route",))
        self._count = self._reg.counter(
            "dl4j_requests_total", "requests by route and status class",
            ("route", "status"))
        self._burn = self._reg.gauge(
            "dl4j_slo_burn_rate",
            "error-budget burn rate over the sliding window: bad_fraction / "
            "(1 - objective); 1.0 = spending budget exactly at the "
            "objective rate", ("route",))
        self._shed = self._reg.counter(
            "dl4j_shed_total",
            "load-shedding decisions by route and reason (backpressure -> "
            "429, deadline -> 503)", ("route", "reason"))
        # token-level generative serving (serve/scheduler.GenerateWorker):
        # a stream's user experience is TTFT + the ITL tail, not one
        # end-to-end latency, so both get their own histograms and their
        # own thresholds into the SAME burn-rate window — a slow first
        # token or a stuttering stream spends error budget exactly like a
        # slow predict() request
        self.ttft_threshold_s = float(
            env("DL4J_TPU_SLO_TTFT_MS",
                env("DL4J_TPU_SLO_LATENCY_MS", "250"))) / 1e3
        self.itl_threshold_s = float(env("DL4J_TPU_SLO_ITL_MS", "100")) / 1e3
        self._ttft = self._reg.histogram(
            "dl4j_ttft_seconds",
            "time to first generated token by route (prompt queue + prefill; "
            "P2 streaming quantiles)", ("route",))
        self._itl = self._reg.histogram(
            "dl4j_itl_seconds",
            "inter-token latency by route (decode-step cadence as the "
            "stream consumer sees it)", ("route",))
        self._tokens = self._reg.counter(
            "dl4j_tokens_generated_total",
            "generated tokens by route (every emitted decode token)",
            ("route",))
        self._occupancy = self._reg.gauge(
            "dl4j_decode_batch_occupancy",
            "streams currently in the token-level continuous decode batch",
            ("model",))
        self._lock = threading.Lock()
        # route -> deque[(perf_counter_ts, is_bad)]
        self._windows: Dict[str, Deque[Tuple[float, bool]]] = {}

    def threshold_for(self, route: str) -> float:
        """Latency threshold for ``route``: the longest
        ``DL4J_TPU_SLO_ROUTE_LATENCY_MS`` prefix that matches, else the
        global default. Different request classes carry different latency
        contracts (a vector search answers in tens of ms, a generate stream
        in seconds); one global number would either page on healthy
        generation or sleep through a slow search tier."""
        best = self.threshold_s
        best_len = -1
        for prefix, thr in self.route_thresholds_s.items():
            if route.startswith(prefix) and len(prefix) > best_len:
                best, best_len = thr, len(prefix)
        return best

    def observe(self, route: str, latency_s: float, status: str = "ok",
                error: bool = False):
        """Record one finished request. Never raises (the serving path must
        not die to bookkeeping)."""
        try:
            self._hist.observe(latency_s, route=route)
            self._count.inc(route=route, status=status)
            self._note_window(
                route, error or latency_s > self.threshold_for(route))
        except Exception:
            pass

    def observe_shed(self, route: str, reason: str = "backpressure"):
        """Record one load-shedding decision (``serve/`` scheduler). A shed
        counts as a BAD request for the burn rate — rejecting traffic spends
        error budget, which is exactly what makes the overload visible on
        the same gauge paging watches for latency violations — but it does
        not enter the latency histogram (a shed has no service latency).
        Never raises."""
        try:
            self._count.inc(route=route, status="shed")
            self._shed.inc(route=route, reason=reason)
            self._note_window(route, True)
        except Exception:
            pass

    def _note_window(self, route: str, bad: bool):
        now = time.perf_counter()
        horizon = now - self.window_s
        with self._lock:
            win = self._windows.get(route)
            if win is None:
                win = self._windows[route] = deque()
            win.append((now, bad))
            while win and win[0][0] < horizon:
                win.popleft()
            n_bad = sum(1 for _, b in win if b)
            rate = (n_bad / len(win)) / (1.0 - self.objective)
        self._burn.set(round(rate, 4), route=route)

    def observe_ttft(self, route: str, latency_s: float):
        """Record one stream's time-to-first-token. Counts the first token
        into the token counter and burns budget when it misses the TTFT
        threshold. Never raises."""
        try:
            self._ttft.observe(latency_s, route=route)
            self._tokens.inc(route=route)
            self._note_window(route, latency_s > self.ttft_threshold_s)
        except Exception:
            pass

    def observe_itl(self, route: str, latency_s: float):
        """Record one inter-token gap; every call is one more generated
        token. A gap over the ITL threshold burns budget — stream stutter
        is an SLO violation even when the total finishes on time. Never
        raises."""
        try:
            self._itl.observe(latency_s, route=route)
            self._tokens.inc(route=route)
            self._note_window(route, latency_s > self.itl_threshold_s)
        except Exception:
            pass

    def set_decode_occupancy(self, model: str, streams: int):
        """Gauge: streams currently holding a decode-batch slot."""
        try:
            self._occupancy.set(int(streams), model=model)
        except Exception:
            pass

    def burn_rate(self, route: str) -> Optional[float]:
        return self._burn.value(route=route)

    def clear(self):
        with self._lock:
            self._windows.clear()


_TRACKER: Optional[SloTracker] = None
_TRACKER_LOCK = threading.Lock()


def slo_tracker() -> SloTracker:
    """Process-global tracker, constructed on first use so env knobs set by
    tests/launchers before the first request are honored."""
    global _TRACKER
    if _TRACKER is None:
        with _TRACKER_LOCK:
            if _TRACKER is None:
                _TRACKER = SloTracker()
    return _TRACKER


def observe_request(route: str, latency_s: float, status: str = "ok",
                    error: bool = False):
    """Module-level convenience; honors the DL4J_TPU_OBS kill switch."""
    from deeplearning4j_tpu import obs

    if obs.enabled():
        slo_tracker().observe(route, latency_s, status=status, error=error)


def observe_shed(route: str, reason: str = "backpressure"):
    """Module-level convenience; honors the DL4J_TPU_OBS kill switch."""
    from deeplearning4j_tpu import obs

    if obs.enabled():
        slo_tracker().observe_shed(route, reason=reason)


def observe_ttft(route: str, latency_s: float):
    """Module-level convenience; honors the DL4J_TPU_OBS kill switch."""
    from deeplearning4j_tpu import obs

    if obs.enabled():
        slo_tracker().observe_ttft(route, latency_s)


def observe_itl(route: str, latency_s: float):
    """Module-level convenience; honors the DL4J_TPU_OBS kill switch."""
    from deeplearning4j_tpu import obs

    if obs.enabled():
        slo_tracker().observe_itl(route, latency_s)


def set_decode_occupancy(model: str, streams: int):
    """Module-level convenience; honors the DL4J_TPU_OBS kill switch."""
    from deeplearning4j_tpu import obs

    if obs.enabled():
        slo_tracker().set_decode_occupancy(model, streams)


def _reset_tracker():
    """Drop the global tracker so the next request re-reads env knobs
    (obs.reset; the registry families are cleared separately)."""
    global _TRACKER
    with _TRACKER_LOCK:
        _TRACKER = None
