"""Render the span ring as Chrome/Perfetto ``trace_event`` JSON.

The span tracer already records everything a timeline needs — start time on
the process ``perf_counter`` clock (``t0_s``), wall duration, thread id and
name, nesting attrs — and the event log carries wall-clock-stamped instants
(checkpoints, chaos faults, guard trips). This module joins the two onto
one microsecond axis and emits the `trace_event format`_ that both
``chrome://tracing`` and https://ui.perfetto.dev load directly:

- every finished span becomes a complete event (``ph: "X"``) on its
  thread's lane, so nested phase spans (``phase.fwd`` under
  ``mln.fit_batch``) render as stacked slices;
- ``compile`` spans keep their ``site``/``mode`` attrs as args (cold-start
  analysis: the compile wall is literally visible);
- event-log records become instant events (``ph: "i"``) — their wall-clock
  ``ts`` is mapped onto the span timeline through the tracer's anchor, a
  (wall, perf_counter) pair sampled back to back at tracer construction;
- thread-name metadata events (``ph: "M"``) label each lane.

Debug/report-time only: nothing here may be called from traced or
per-batch code (enforced by the ``cost-analysis-off-hot-path`` lint rule).

Two front doors:

- ``python -m deeplearning4j_tpu.obs.trace_export --out trace.json``
  renders a ``DL4J_TPU_SPAN_DUMP`` file (``--spans``) and optionally a
  ``DL4J_TPU_EVENT_LOG`` JSONL (``--events``) offline;
- ``GET /debug/trace`` on ``ui/server.py`` renders the live ring of the
  serving process.

.. _trace_event format: https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional

__all__ = ["trace_events", "render", "live_trace", "merge", "validate",
           "main"]

_PID = 1  # single-process timeline; lanes are threads (merge() re-pids)


def trace_events(spans: Iterable[dict],
                 events: Iterable[dict] = (),
                 anchor: Optional[Dict[str, float]] = None) -> dict:
    """Build the ``{"traceEvents": [...]}`` document from span-ring records
    (``SpanTracer.recent()`` / a ``DL4J_TPU_SPAN_DUMP`` file) plus optional
    event-log records. Spans without ``t0_s`` (records from a pre-profiling
    ring) are skipped rather than guessed at."""
    out: List[dict] = []
    threads: Dict[int, str] = {}
    for rec in spans:
        t0 = rec.get("t0_s")
        if t0 is None:
            continue
        tid = int(rec.get("tid") or 0)
        threads.setdefault(tid, str(rec.get("thread") or f"thread-{tid}"))
        name = rec["span"]
        attrs = rec.get("attrs") or {}
        if name == "compile" and "site" in attrs:
            name = f"compile:{attrs['site']}"
        args = dict(attrs)
        args["cpu_ms"] = round(rec.get("cpu_s", 0.0) * 1e3, 3)
        if rec.get("parent"):
            args["parent"] = rec["parent"]
        if rec.get("error"):
            args["error"] = True
        out.append({
            "name": name,
            "cat": "span",
            "ph": "X",
            "ts": t0 * 1e6,
            "dur": max(rec.get("wall_s", 0.0), 0.0) * 1e6,
            "pid": _PID,
            "tid": tid,
            "args": args,
        })
    if events and anchor:
        # wall = anchor.wall_s + (perf - anchor.perf_s)  =>  invert for ts
        wall0, perf0 = anchor.get("wall_s"), anchor.get("perf_s")
        if wall0 is not None and perf0 is not None:
            for ev in events:
                ts = ev.get("ts")
                kind = ev.get("kind")
                if ts is None or kind is None:
                    continue
                args = {k: v for k, v in ev.items() if k not in ("ts", "kind")}
                out.append({
                    "name": str(kind),
                    "cat": "event",
                    "ph": "i",
                    "s": "p",
                    "ts": (perf0 + (float(ts) - wall0)) * 1e6,
                    "pid": _PID,
                    "tid": 0,
                    "args": args,
                })
    for tid, tname in sorted(threads.items()):
        out.append({
            "name": "thread_name",
            "ph": "M",
            "pid": _PID,
            "tid": tid,
            "args": {"name": tname},
        })
    out.sort(key=lambda e: (e["ph"] == "M", e.get("ts", 0.0)))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def render(spans: Iterable[dict], events: Iterable[dict] = (),
           anchor: Optional[Dict[str, float]] = None) -> str:
    return json.dumps(trace_events(spans, events, anchor))


def live_trace(include_events: bool = False) -> str:
    """Render the current process's span ring (the ``/debug/trace`` body).
    Event-log instants are only available when a file sink is configured
    and ``include_events`` is set (the log is the only durable store)."""
    from deeplearning4j_tpu.obs import events as events_mod
    from deeplearning4j_tpu.obs import spans as spans_mod

    tr = spans_mod.tracer()
    evs: List[dict] = []
    if include_events:
        path = events_mod.event_log().path
        if path:
            evs = _read_events(path)
    return render(tr.recent(), evs, tr.anchor())


def merge(dumps: Iterable[dict],
          events_seq: Optional[List[List[dict]]] = None) -> dict:
    """Join per-process span dumps (``SpanTracer.dump`` docs) into ONE
    timeline: every process becomes its own Perfetto track (distinct
    ``pid`` + ``process_name`` metadata naming its rank/wid/host), with
    all tracks aligned on a common wall-clock axis through each dump's own
    wall↔perf anchor — cross-host alignment never assumes the hosts agree
    about *when*, only that each process sampled its anchor pair back to
    back. ``events_seq`` optionally carries each dump's event-log records
    (same order). Report-time only."""
    merged: List[dict] = []
    offsets: List[float] = []
    for i, dump in enumerate(dumps):
        anchor = dump.get("anchor") if isinstance(dump, dict) else None
        spans = dump.get("spans", []) if isinstance(dump, dict) else dump
        evs = (events_seq[i] if events_seq and i < len(events_seq) else ())
        doc = trace_events(spans, evs, anchor)
        proc = (dump.get("process") or {}) if isinstance(dump, dict) else {}
        pid = i + 1
        rank = proc.get("rank")
        label = (f"rank {rank}" if rank is not None else f"proc {pid}")
        if proc.get("wid"):
            label += f" ({proc['wid']})"
        if proc.get("host"):
            label += f" @{proc['host']}"
        # perf-axis µs -> wall-axis µs: shift by this dump's own anchor
        off = 0.0
        if isinstance(anchor, dict) and \
                anchor.get("wall_s") is not None and \
                anchor.get("perf_s") is not None:
            off = (float(anchor["wall_s"]) - float(anchor["perf_s"])) * 1e6
        for e in doc["traceEvents"]:
            e["pid"] = pid
            if "ts" in e:
                e["ts"] += off
                offsets.append(e["ts"])
        merged.extend(doc["traceEvents"])
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
    if offsets:
        # normalize so the merged timeline starts near zero (epoch-scale µs
        # values render, but pan/zoom UX is much better from the origin)
        t0 = min(offsets)
        for e in merged:
            if "ts" in e:
                e["ts"] -= t0
    merged.sort(key=lambda e: (e["ph"] == "M", e.get("ts", 0.0)))
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def validate(doc: dict) -> List[str]:
    """Schema + nesting sanity of a trace document. Returns problems (empty
    = loadable). Checks: top-level shape, required per-event fields, and
    that complete events on each (process, thread) lane are properly nested
    (a child slice must lie inside its enclosing slice — exactly what
    Perfetto requires to stack them)."""
    problems: List[str] = []
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    lanes: Dict[tuple, List[dict]] = {}
    for i, e in enumerate(evs):
        if not isinstance(e, dict) or "ph" not in e or "name" not in e:
            problems.append(f"event {i}: missing ph/name")
            continue
        if e["ph"] == "X":
            if not isinstance(e.get("ts"), (int, float)) or \
                    not isinstance(e.get("dur"), (int, float)):
                problems.append(f"event {i} ({e['name']}): bad ts/dur")
                continue
            lanes.setdefault(
                (int(e.get("pid", 0)), int(e.get("tid", 0))), []).append(e)
        elif e["ph"] == "i" and not isinstance(e.get("ts"), (int, float)):
            problems.append(f"event {i} ({e['name']}): instant without ts")
    eps = 1e-3  # µs slack for float rounding at the boundaries
    for tid, lane in lanes.items():
        lane.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[dict] = []
        for e in lane:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] - eps:
                stack.pop()
            if stack:
                parent = stack[-1]
                if e["ts"] + e["dur"] > parent["ts"] + parent["dur"] + eps:
                    problems.append(
                        f"lane {tid}: {e['name']} overlaps {parent['name']} "
                        "without nesting")
            stack.append(e)
    return problems


def _read_events(path: str) -> List[dict]:
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn rotation line
    except OSError:
        pass
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.obs.trace_export",
        description="Render a DL4J_TPU_SPAN_DUMP file (+ optional event log) "
                    "as Chrome/Perfetto trace_event JSON.")
    ap.add_argument("--spans", required=True, nargs="+",
                    help="span dump JSON written by DL4J_TPU_SPAN_DUMP or "
                         "SpanTracer.dump(); several files merge into one "
                         "multi-process timeline (one track per dump)")
    ap.add_argument("--events", default=None, nargs="*",
                    help="optional DL4J_TPU_EVENT_LOG JSONL to overlay as "
                         "instant events (with several --spans, matched by "
                         "position)")
    ap.add_argument("--out", default="-",
                    help="output path (default stdout)")
    ap.add_argument("--validate", action="store_true",
                    help="also run schema/nesting validation; non-zero exit "
                         "on problems")
    args = ap.parse_args(argv)

    dumps = []
    for path in args.spans:
        with open(path, "r", encoding="utf-8") as f:
            dumps.append(json.load(f))
    ev_paths = args.events or []
    if len(dumps) == 1:
        dump = dumps[0]
        spans = dump.get("spans", dump if isinstance(dump, list) else [])
        anchor = dump.get("anchor") if isinstance(dump, dict) else None
        events = _read_events(ev_paths[0]) if ev_paths else []
        doc = trace_events(spans, events, anchor)
    else:
        events_seq = [_read_events(p) for p in ev_paths] or None
        doc = merge(dumps, events_seq)
    text = json.dumps(doc)
    if args.out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    n_spans = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
    sys.stderr.write(f"trace_export: {n_spans} spans, "
                     f"{sum(1 for e in doc['traceEvents'] if e['ph'] == 'i')} "
                     f"instants -> {args.out}\n")
    if args.validate:
        problems = validate(doc)
        for p in problems:
            sys.stderr.write(f"trace_export: INVALID: {p}\n")
        return 1 if problems else 0
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI shim
    raise SystemExit(main())
