"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints (ISSUE 5 / the hot-path discipline the rest of the repo
already follows):

- **Host-side scalars only.** Nothing here touches jax; callers must never
  pass device arrays. Recording is a dict update under a lock — cheap enough
  for per-iteration use. ``block_until_ready`` is never called on the hot
  path; if a value needs a device sync to exist, it is not a metric.
- **Label families.** A metric name plus a fixed tuple of label names forms
  a family; each distinct label-value combination is one series. This is the
  Prometheus data model, so exposition is a straight rendering.
- **Bounded memory.** Histograms keep (count, sum, min, max) exactly and a
  bounded reservoir of recent observations for quantiles; series counts are
  bounded by the code's own label cardinality (sites, buckets, event kinds).
- **Mergeable across processes.** Every histogram series also maintains
  fixed log-spaced bucket counts (``BUCKET_BOUNDS``, identical in every
  process by construction). Quantiles of per-process quantiles are wrong;
  bucket counts ADD, so the fleet collector (obs/fleet.py) merges worker
  snapshots by summing counts and re-derives federated quantiles with
  :func:`quantile_from_buckets`.

The registry is process-global (``registry()``); ``bucketing.telemetry()``
is an adapter shim over families registered here (utils/bucketing.py), so
every counter that existed before this layer is scrapeable at /metrics.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "BUCKET_BOUNDS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "quantile_from_buckets",
    "registry",
]

_RESERVOIR = 256  # recent observations kept per histogram series (debug view)


def _default_bounds() -> Tuple[float, ...]:
    # 1/2.5/5 ladder per decade from 1µ to 500k: covers latencies (µs..hours),
    # batch rows, and byte-ish magnitudes with one shared, process-invariant
    # ladder — identical bounds everywhere is what makes counts mergeable.
    out: List[float] = []
    for exp in range(-6, 6):
        for m in (1.0, 2.5, 5.0):
            out.append(m * 10.0 ** exp)
    return tuple(out)


BUCKET_BOUNDS: Tuple[float, ...] = _default_bounds()


def quantile_from_buckets(counts: Sequence[float], q: float,
                          bounds: Sequence[float] = BUCKET_BOUNDS) -> float:
    """Quantile estimate from (possibly merged) per-bucket counts.
    ``counts`` is non-cumulative with ``len(bounds) + 1`` entries (the last
    is the overflow bucket); linear interpolation inside the landing
    bucket."""
    total = float(sum(counts))
    if total <= 0:
        return 0.0
    target = min(max(q, 0.0), 1.0) * total
    acc = 0.0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target and c > 0:
            if i >= len(bounds):
                return float(bounds[-1])  # overflow bucket: clamp
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (target - (acc - c)) / c
            return lo + (float(bounds[i]) - lo) * frac
    return float(bounds[-1])

# Quantiles tracked per histogram series via P² estimators (streaming, O(1)
# memory per quantile — serving SLOs need p95/p99 that stay correct over
# millions of observations, which the bounded recent-window reservoir
# cannot provide).
_QUANTILES = (0.50, 0.90, 0.95, 0.99)


class _P2Quantile:
    """P² streaming quantile estimator (Jain & Chlamtac, CACM 1985).

    Five markers track (min, p/2, p, (1+p)/2, max); marker heights are
    nudged by parabolic (falling back to linear) interpolation as counts
    drift from their desired positions. O(1) memory and O(1) update —
    exact for the first five observations, within a fraction of a percent
    of the true quantile for well-behaved streams after that."""

    __slots__ = ("p", "n", "q", "pos")

    def __init__(self, p: float):
        self.p = p
        self.n = 0
        self.q: List[float] = []      # marker heights (sorted)
        self.pos = [0, 1, 2, 3, 4]    # marker positions (0-based)

    def add(self, x: float):
        if self.n < 5:
            self.q.append(x)
            self.q.sort()
            self.n += 1
            return
        q, pos, p = self.q, self.pos, self.p
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            for i in range(1, 4):
                if x < q[i]:
                    break
                k = i
        self.n += 1
        for i in range(k + 1, 5):
            pos[i] += 1
        last = self.n - 1
        desired = (0.0, last * p / 2.0, last * p,
                   last * (1.0 + p) / 2.0, float(last))
        for i in (1, 2, 3):
            d = desired[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1) or \
                    (d <= -1.0 and pos[i - 1] - pos[i] < -1):
                s = 1 if d >= 1.0 else -1
                qn = self._parabolic(i, s)
                if not (q[i - 1] < qn < q[i + 1]):
                    qn = self._linear(i, s)
                q[i] = qn
                pos[i] += s

    def _parabolic(self, i: int, s: int) -> float:
        q, n = self.q, self.pos
        return q[i] + s / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + s) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - s) * (q[i] - q[i - 1]) / (n[i] - n[i - 1]))

    def _linear(self, i: int, s: int) -> float:
        q, n = self.q, self.pos
        return q[i] + s * (q[i + s] - q[i]) / (n[i + s] - n[i])

    def value(self) -> float:
        if not self.q:
            return 0.0
        if self.n < 5:
            # exact small-sample quantile (nearest-rank on the sorted list)
            idx = min(len(self.q) - 1, int(self.p * len(self.q)))
            return self.q[idx]
        return self.q[2]


def _label_key(label_names: Tuple[str, ...], labels: Dict[str, object]) -> Tuple[str, ...]:
    if set(labels) != set(label_names):
        raise ValueError(
            f"expected labels {label_names}, got {tuple(sorted(labels))}")
    return tuple(str(labels[k]) for k in label_names)


class _Family:
    """Shared series bookkeeping for one named metric family."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str]):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}

    def clear(self):
        """Drop every series (tests / bench isolation)."""
        with self._lock:
            self._series.clear()

    def series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._series.items())


class Counter(_Family):
    """Monotonically increasing count. ``inc`` returns the new value so
    callers can detect first-touch (e.g. bucket promotion) in one step."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            v = self._series.get(key, 0) + amount
            self._series[key] = v
            return v

    def value(self, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0)

    def as_dict(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)


class Gauge(_Family):
    """Last-write-wins scalar (configuration values, current score, ...)."""

    kind = "gauge"

    def set(self, value: float, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = value

    def value(self, **labels) -> Optional[float]:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key)

    def as_dict(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)


class _HistSeries:
    __slots__ = ("count", "total", "min", "max", "reservoir", "quantiles",
                 "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.reservoir = deque(maxlen=_RESERVOIR)
        self.quantiles = tuple(_P2Quantile(p) for p in _QUANTILES)
        # non-cumulative counts over BUCKET_BOUNDS (+1 overflow bucket):
        # the mergeable export — counts add across processes
        self.buckets = [0] * (len(BUCKET_BOUNDS) + 1)


class Histogram(_Family):
    """count/sum/min/max exactly, P² streaming estimators for
    p50/p90/p95/p99 over the whole stream, plus a bounded reservoir of the
    most recent observations (debug view via ``recent``). Rendered as a
    Prometheus summary (quantile series + _sum/_count)."""

    kind = "histogram"

    def observe(self, value: float, **labels):
        key = _label_key(self.label_names, labels)
        v = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries()
            s.count += 1
            s.total += v
            if v < s.min:
                s.min = v
            if v > s.max:
                s.max = v
            s.reservoir.append(v)
            s.buckets[bisect_left(BUCKET_BOUNDS, v)] += 1
            for est in s.quantiles:
                est.add(v)

    def summary(self, **labels) -> Optional[dict]:
        key = _label_key(self.label_names, labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            return self._summarize(s)

    @staticmethod
    def _summarize(s: "_HistSeries") -> dict:
        out = {
            "count": s.count,
            "sum": s.total,
            "min": s.min if s.count else 0.0,
            "max": s.max if s.count else 0.0,
        }
        for est in s.quantiles:
            out[f"p{int(est.p * 100)}"] = est.value()
        out["buckets"] = list(s.buckets)
        return out

    def as_dict(self) -> Dict[Tuple[str, ...], dict]:
        with self._lock:
            return {k: self._summarize(s) for k, s in self._series.items()}


class MetricsRegistry:
    """Name -> family map with get-or-create accessors. Re-registering a
    name returns the existing family; a kind or label mismatch is a
    programming error and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, cls, name: str, help: str, label_names: Sequence[str]):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind} "
                        f"with labels {fam.label_names}")
                return fam
            fam = cls(name, help, label_names)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "", label_names: Sequence[str] = ()) -> Histogram:
        return self._get(Histogram, name, help, label_names)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def reset(self):
        """Clear every series but keep family registrations (shims hold
        references to their families, so dropping them would orphan those)."""
        for fam in self.families():
            fam.clear()

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-friendly {name: {"label=value|...": value-or-summary}}."""
        out: Dict[str, dict] = {}
        for fam in self.families():
            series = {}
            for key, _ in fam.series():
                labels = dict(zip(fam.label_names, key))
                skey = "|".join(f"{k}={v}" for k, v in labels.items()) or ""
                if isinstance(fam, Histogram):
                    series[skey] = fam.summary(**labels)
                elif isinstance(fam, (Counter, Gauge)):
                    series[skey] = fam.value(**labels)
            out[fam.name] = series
        return out

    def export(self) -> dict:
        """Typed dump for cross-process federation (obs/fleet.py): unlike
        ``snapshot()`` this keeps each family's kind/help/label names, so a
        collector that never imported the producing code can re-render a
        correct exposition. Histogram series carry the mergeable bucket
        counts (``BUCKET_BOUNDS`` ladder)."""
        out: Dict[str, dict] = {}
        for fam in self.families():
            series: Dict[str, object] = {}
            for key, _ in fam.series():
                labels = dict(zip(fam.label_names, key))
                skey = "|".join(f"{k}={v}" for k, v in labels.items()) or ""
                if isinstance(fam, Histogram):
                    series[skey] = fam.summary(**labels)
                elif isinstance(fam, (Counter, Gauge)):
                    series[skey] = fam.value(**labels)
            out[fam.name] = {
                "kind": fam.kind,
                "help": fam.help,
                "labels": list(fam.label_names),
                "series": series,
            }
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {_esc_help(fam.help)}")
            kind = "summary" if isinstance(fam, Histogram) else fam.kind
            lines.append(f"# TYPE {fam.name} {kind}")
            for key, _ in fam.series():
                labels = dict(zip(fam.label_names, key))
                if isinstance(fam, Histogram):
                    s = fam.summary(**labels)
                    for qname, qval in (("0.5", s["p50"]), ("0.9", s["p90"]),
                                        ("0.95", s["p95"]), ("0.99", s["p99"])):
                        lines.append(_sample(fam.name, {**labels, "quantile": qname}, qval))
                    lines.append(_sample(fam.name + "_sum", labels, s["sum"]))
                    lines.append(_sample(fam.name + "_count", labels, s["count"]))
                else:
                    lines.append(_sample(fam.name, labels, fam.value(**labels)))
        return "\n".join(lines) + "\n"


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sample(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        body = ",".join(f'{k}="{_esc_label(str(v))}"' for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {_num(value)}"
    return f"{name} {_num(value)}"


def _num(v) -> str:
    if v is None:
        return "0"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY
