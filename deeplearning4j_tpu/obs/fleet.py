"""Fleet observability plane: trace propagation, federation, stragglers.

The obs layer (metrics/spans/events) is process-local by design; PRs 15/19
made the system a fleet — elastic workers across processes and a
multi-worker serving front door. This module is the cross-process half:

- **Trace context** — a W3C-style ``traceparent`` (``00-<32 hex
  trace_id>-<16 hex span_id>-<2 hex flags>``) minted (or adopted) at the
  HTTP front door (serve/httpcommon.py), held in a thread-local scope for
  the request's lifetime, and stamped onto every span/event recorded while
  the scope is open. The scheduler carries it across the coalescing
  boundary so a batched dispatch span lists the trace ids it served.
- **Process context** — ``set_process_context(rank=..., wid=...,
  incarnation=..., slice=...)``; elastic workers call it at every view
  adoption so spans and JSONL event lines are rank/incarnation-tagged
  (``DL4J_TPU_RANK``/``DL4J_TPU_WID``/``DL4J_TPU_SLICE`` seed it for
  processes launched with the knobs already decided).
- **Metrics federation** — :func:`publish_snapshot` writes this process's
  registry export (mergeable bucket histograms, obs/metrics.py) into the
  elastic store under ``obs/snap/<wid>`` (CRC-framed like every other key);
  :class:`FleetCollector` reads every snapshot back and renders ONE
  Prometheus exposition with ``rank``/``slice``/``incarnation`` labels plus
  fleet roll-ups (counters summed, histogram buckets merged, federated
  quantiles via :func:`metrics.quantile_from_buckets`).
- **Straggler detection** — :class:`StragglerDetector` consumes per-rank
  step walls (published by ``train/elastic.py`` at iteration boundaries
  under ``obs/stepwall/<gen>/<it>/<rank>``), maintains the
  ``dl4j_step_skew_seconds{rank}`` gauge and emits one
  ``straggler_detected`` event when a rank exceeds median ×
  ``DL4J_TPU_STRAGGLER_FACTOR`` (default 2.0) for
  ``DL4J_TPU_STRAGGLER_PATIENCE`` (default 3) consecutive boundaries.

Report-time discipline: :func:`publish_snapshot`,
:meth:`FleetCollector.collect_snapshots`, and the collector exposition do
store round-trips and whole-registry serialization — none may be reachable
from traced or per-batch dispatch code (enforced by the
``cost-analysis-off-hot-path`` lint rule). The stamping helpers
(:func:`stamp_span`/:func:`stamp_event`) are the only pieces that ride the
hot path and they are dict updates that never raise.

CLI::

    python -m deeplearning4j_tpu.obs.fleet serve  --store DIR|tcp://…  --port 0
    python -m deeplearning4j_tpu.obs.fleet render --store DIR|tcp://…
"""

from __future__ import annotations

import argparse
import json
import os
import re
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.obs import metrics

__all__ = [
    "FleetCollector",
    "OBS_PREFIX",
    "SNAP_PREFIX",
    "STEPWALL_PREFIX",
    "StragglerDetector",
    "TraceContext",
    "current_trace",
    "main",
    "process_context",
    "publish_snapshot",
    "serve_collector",
    "set_current_trace",
    "set_process_context",
    "stamp_event",
    "stamp_span",
    "stepwall_key",
    "trace_scope",
]

OBS_PREFIX = "obs/"
SNAP_PREFIX = OBS_PREFIX + "snap/"
STEPWALL_PREFIX = OBS_PREFIX + "stepwall/"

_HOST = socket.gethostname()

# ---------------------------------------------------------------------------
# Trace context (W3C traceparent)
# ---------------------------------------------------------------------------

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


class TraceContext:
    """One hop of a distributed trace: ``trace_id`` names the request end
    to end, ``span_id`` names this process's segment of it."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    @staticmethod
    def mint() -> "TraceContext":
        return TraceContext(os.urandom(16).hex(), os.urandom(8).hex())

    @staticmethod
    def parse(header: Optional[str]) -> Optional["TraceContext"]:
        """``traceparent`` header -> context, or None when absent/invalid
        (the caller mints a fresh root instead of failing the request)."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if not m:
            return None
        trace_id, span_id = m.group(1), m.group(2)
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None  # all-zero ids are invalid per the W3C spec
        return TraceContext(trace_id, span_id)

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — what a server does with an inbound
        context before doing its own work."""
        return TraceContext(self.trace_id, os.urandom(8).hex())

    def header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TraceContext({self.header()!r})"


_TLS = threading.local()


def current_trace() -> Optional[TraceContext]:
    return getattr(_TLS, "trace", None)


def set_current_trace(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as this thread's active trace; returns the previous
    one so callers can restore it (see :func:`trace_scope`)."""
    prev = getattr(_TLS, "trace", None)
    _TLS.trace = ctx
    return prev


class trace_scope:
    """``with trace_scope(ctx): ...`` — thread-local trace window; every
    span/event recorded inside carries ``ctx``'s ids."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._prev: Optional[TraceContext] = None

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = set_current_trace(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        set_current_trace(self._prev)
        return False


# ---------------------------------------------------------------------------
# Process context (rank/incarnation tagging)
# ---------------------------------------------------------------------------

_CTX_LOCK = threading.Lock()
_PROC_CTX: Dict[str, object] = {}
_CTX_ENV_CHECKED = False


def _maybe_adopt_env():
    # lazy: a worker launched with the identity already decided
    # (tools/obs_smoke.sh bench arm, ad-hoc scripts) inherits it without an
    # explicit set_process_context call. Every caller holds _CTX_LOCK (the
    # lock is not reentrant, so it cannot be re-acquired here).
    global _CTX_ENV_CHECKED
    if _CTX_ENV_CHECKED:
        return
    _CTX_ENV_CHECKED = True
    env = os.environ.get
    rank = env("DL4J_TPU_RANK")
    if rank is not None and rank.lstrip("-").isdigit():
        _PROC_CTX.setdefault("rank", int(rank))  # graftlint: disable=lock-discipline
    for key, var in (("wid", "DL4J_TPU_WID"), ("slice", "DL4J_TPU_SLICE")):
        val = env(var)
        if val:
            _PROC_CTX.setdefault(key, val)  # graftlint: disable=lock-discipline


def set_process_context(**fields):
    """Merge identity fields (``rank``, ``wid``, ``incarnation``, ``slice``)
    into the process context; a None value removes the field. Elastic
    workers call this at every view adoption — rank changes across reforms
    and span/event records carry the rank current when recorded."""
    with _CTX_LOCK:
        _maybe_adopt_env()
        for k, v in fields.items():
            if v is None:
                _PROC_CTX.pop(k, None)
            else:
                _PROC_CTX[k] = v


def process_context() -> Dict[str, object]:
    """host/pid plus whatever identity has been set — the block stamped
    into span dumps and federation snapshots."""
    with _CTX_LOCK:
        _maybe_adopt_env()
        out: Dict[str, object] = {"host": _HOST, "pid": os.getpid()}
        out.update(_PROC_CTX)
        return out


def _reset_for_tests():
    global _CTX_ENV_CHECKED
    with _CTX_LOCK:
        _PROC_CTX.clear()
        _CTX_ENV_CHECKED = False
    set_current_trace(None)


def stamp_span(rec: Dict[str, object]) -> None:
    """Tag one finished-span record in place (obs/spans.py calls this per
    pop). Hot-path: a few dict reads/writes, never raises."""
    try:
        rank = _PROC_CTX.get("rank")
        if rank is not None:
            rec["rank"] = rank
            inc = _PROC_CTX.get("incarnation")
            if inc is not None:
                rec["inc"] = inc
        ctx = getattr(_TLS, "trace", None)
        if ctx is not None:
            rec["trace_id"] = ctx.trace_id
            rec["span_id"] = ctx.span_id
    except Exception:
        pass


def stamp_event(rec: Dict[str, object]) -> None:
    """Tag one event-log record in place (obs/events.py calls this per
    emit): host/pid always, plus ``perf_s`` — the (ts, perf_s) pair on
    every line IS a wall↔perf anchor, so merged timelines never rely on
    hosts agreeing about wall-clock. Rank/incarnation/trace ride along when
    set. Hot-path: never raises."""
    try:
        rec.setdefault("host", _HOST)
        rec.setdefault("pid", os.getpid())
        rec.setdefault("perf_s", time.perf_counter())
        rank = _PROC_CTX.get("rank")
        if rank is not None:
            rec.setdefault("rank", rank)
            inc = _PROC_CTX.get("incarnation")
            if inc is not None:
                rec.setdefault("inc", inc)
        ctx = getattr(_TLS, "trace", None)
        if ctx is not None:
            rec.setdefault("trace_id", ctx.trace_id)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Metrics federation: publish + collect/merge
# ---------------------------------------------------------------------------

def publish_snapshot(store, wid: str, extra: Optional[dict] = None) -> str:
    """Serialize this process's registry into the elastic store under
    ``obs/snap/<wid>`` (last write wins — the store frames it with a CRC
    like every other key). Report-time only: serializes every family and
    does a store round-trip; never call from traced/per-batch code
    (cost-analysis-off-hot-path). Returns the key written."""
    from deeplearning4j_tpu import obs
    from deeplearning4j_tpu.obs import spans as spans_mod

    doc = {
        "wid": str(wid),
        "ts": time.time(),  # graftlint: disable=monotonic-clock
        "process": process_context(),
        "anchor": spans_mod.tracer().anchor(),
        "bucket_bounds": list(metrics.BUCKET_BOUNDS),
        "families": metrics.registry().export(),
        "spans": spans_mod.tracer().summary(),
        "events": obs.event_log().counts(),
    }
    if extra:
        doc.update(extra)
    key = SNAP_PREFIX + str(wid)
    store.set(key, json.dumps(doc, default=str).encode("utf-8"))
    return key


def stepwall_key(gen: int, iteration: int, rank: int) -> str:
    return f"{STEPWALL_PREFIX}{int(gen)}/{int(iteration)}/{int(rank)}"


class FleetCollector:
    """Merge every worker's published snapshot into one exposition.

    Per-worker series keep their original labels plus ``rank``/``slice``/
    ``incarnation``; roll-ups get a ``_fleet`` suffix: counters sum across
    workers, histogram bucket counts add and federated quantiles are
    re-derived from the merged ladder (quantiles-of-quantiles would be
    wrong — obs/metrics.py)."""

    def __init__(self, store):
        self.store = store

    # -- reading ------------------------------------------------------------

    def collect_snapshots(self) -> List[dict]:
        """Read every ``obs/snap/*`` key; torn/unparseable payloads are
        skipped (a publisher may die mid-run; the CRC framing already
        rejects torn writes). Sorted by wid for stable output."""
        out: List[dict] = []
        for name in self.store.list(SNAP_PREFIX):
            # list() yields names relative to the prefix directory
            raw = self.store.get(SNAP_PREFIX + name)
            if raw is None:
                continue
            try:
                doc = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue
            if isinstance(doc, dict):
                out.append(doc)
        out.sort(key=lambda d: str(d.get("wid", "")))
        return out

    # -- rendering ----------------------------------------------------------

    @staticmethod
    def _worker_labels(doc: dict) -> Dict[str, str]:
        proc = doc.get("process") or {}
        out = {"rank": str(proc.get("rank", "")),
               "slice": str(proc.get("slice", "")),
               "incarnation": str(proc.get("incarnation", ""))}
        return out

    @staticmethod
    def _parse_skey(skey: str) -> Dict[str, str]:
        if not skey:
            return {}
        out: Dict[str, str] = {}
        for pair in skey.split("|"):
            k, _, v = pair.partition("=")
            out[k] = v
        return out

    def prometheus_text(self) -> str:
        """One Prometheus text exposition (0.0.4) over every snapshot.
        Report-time only — never call from traced/per-batch code."""
        snaps = self.collect_snapshots()
        lines: List[str] = [
            "# TYPE dl4j_fleet_workers gauge",
            metrics._sample("dl4j_fleet_workers", {}, len(snaps)),
        ]
        # name -> {"kind", "help", per-worker sample lines}
        fam_lines: Dict[str, List[str]] = {}
        fam_kind: Dict[str, str] = {}
        fam_help: Dict[str, str] = {}
        # roll-ups keyed (name, orig-label skey)
        counter_sums: Dict[str, Dict[str, float]] = {}
        hist_merge: Dict[str, Dict[str, dict]] = {}
        for doc in snaps:
            wlabels = self._worker_labels(doc)
            fams = doc.get("families") or {}
            for name in sorted(fams):
                fam = fams[name]
                kind = fam.get("kind", "untyped")
                fam_kind.setdefault(name, kind)
                fam_help.setdefault(name, fam.get("help", ""))
                bucket = fam_lines.setdefault(name, [])
                for skey, val in sorted((fam.get("series") or {}).items()):
                    # identity labels fill in around the series' own labels
                    # — a family that already carries e.g. a ``rank`` label
                    # (dl4j_step_skew_seconds) keeps it, publisher identity
                    # never clobbers it
                    labels = dict(wlabels)
                    labels.update(self._parse_skey(skey))
                    if kind == "histogram" and isinstance(val, dict):
                        bucket.append(metrics._sample(
                            name + "_sum", labels, val.get("sum", 0.0)))
                        bucket.append(metrics._sample(
                            name + "_count", labels, val.get("count", 0)))
                        merged = hist_merge.setdefault(name, {}).setdefault(
                            skey, {"sum": 0.0, "count": 0, "buckets": None})
                        merged["sum"] += float(val.get("sum", 0.0))
                        merged["count"] += int(val.get("count", 0))
                        counts = val.get("buckets")
                        if isinstance(counts, list):
                            if merged["buckets"] is None:
                                merged["buckets"] = [0] * len(counts)
                            if len(merged["buckets"]) == len(counts):
                                for i, c in enumerate(counts):
                                    merged["buckets"][i] += c
                    else:
                        bucket.append(metrics._sample(name, labels, val))
                        if kind == "counter":
                            sums = counter_sums.setdefault(name, {})
                            sums[skey] = sums.get(skey, 0.0) + float(val or 0)
        for name in sorted(fam_lines):
            kind = fam_kind[name]
            if fam_help.get(name):
                lines.append(
                    f"# HELP {name} {metrics._esc_help(fam_help[name])}")
            # per-worker histogram series render as untyped sum/count pairs;
            # the merged _fleet family below is the real summary
            lines.append(f"# TYPE {name} "
                         f"{'untyped' if kind == 'histogram' else kind}")
            lines.extend(fam_lines[name])
        for name in sorted(counter_sums):
            lines.append(f"# TYPE {name}_fleet counter")
            for skey, total in sorted(counter_sums[name].items()):
                lines.append(metrics._sample(
                    name + "_fleet", self._parse_skey(skey), total))
        for name in sorted(hist_merge):
            lines.append(f"# TYPE {name}_fleet summary")
            for skey, m in sorted(hist_merge[name].items()):
                labels = self._parse_skey(skey)
                counts = m["buckets"] or []
                for q in (0.5, 0.95, 0.99):
                    lines.append(metrics._sample(
                        name + "_fleet", {**labels, "quantile": str(q)},
                        metrics.quantile_from_buckets(counts, q)))
                lines.append(metrics._sample(
                    name + "_fleet_sum", labels, m["sum"]))
                lines.append(metrics._sample(
                    name + "_fleet_count", labels, m["count"]))
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Straggler detection
# ---------------------------------------------------------------------------

class StragglerDetector:
    """Flag ranks whose per-step work wall exceeds the group median ×
    ``factor`` for ``patience`` consecutive boundaries. Feed it the
    complete per-rank wall map for one iteration (train/elastic.py reads
    the previous boundary's ``obs/stepwall`` keys — all published before
    any rank can finish the next step, so no waiting is ever needed).

    Maintains ``dl4j_step_skew_seconds{rank}`` (wall minus group median)
    and emits one ``straggler_detected`` event per rank per flagging."""

    def __init__(self, factor: Optional[float] = None,
                 patience: Optional[int] = None):
        env = os.environ.get
        if factor is None:
            try:
                factor = float(env("DL4J_TPU_STRAGGLER_FACTOR", "2.0"))
            except ValueError:
                factor = 2.0
        if patience is None:
            try:
                patience = int(env("DL4J_TPU_STRAGGLER_PATIENCE", "3"))
            except ValueError:
                patience = 3
        self.factor = max(1.0, float(factor))
        self.patience = max(1, int(patience))
        self._over: Dict[int, int] = {}
        self.flagged: set = set()

    def observe(self, iteration: int, walls: Dict[int, float]) -> List[int]:
        """One boundary's per-rank walls -> ranks newly flagged. Never
        raises (telemetry must not take the step loop down)."""
        try:
            from deeplearning4j_tpu import obs

            if len(walls) < 2:
                return []
            ordered = sorted(walls.values())
            # LOWER median: with 2 ranks an averaged median sits between
            # the fast and slow rank, making wall > median * factor
            # unsatisfiable for any factor >= 2 (w1 > w0 + w1); anchoring
            # on the lower middle keeps the threshold meaningful at every
            # world size
            median = ordered[(len(ordered) - 1) // 2]
            skew = obs.gauge(
                "dl4j_step_skew_seconds",
                "per-rank step work-wall minus the group median at the last "
                "observed boundary (straggler detection input)", ("rank",))
            newly: List[int] = []
            for rank, wall in sorted(walls.items()):
                skew.set(round(wall - median, 6), rank=rank)
                if median > 0 and wall > median * self.factor:
                    self._over[rank] = self._over.get(rank, 0) + 1
                else:
                    self._over[rank] = 0
                    continue
                if self._over[rank] >= self.patience \
                        and rank not in self.flagged:
                    self.flagged.add(rank)
                    newly.append(rank)
                    obs.event("straggler_detected", rank=int(rank),
                              iteration=int(iteration),
                              wall_s=round(float(wall), 6),
                              median_s=round(float(median), 6),
                              factor=self.factor, patience=self.patience)
            return newly
        except Exception:
            return []


# ---------------------------------------------------------------------------
# Collector server + CLI
# ---------------------------------------------------------------------------

def serve_collector(store, port: int = 0):
    """Mount ``/fleet/metrics`` (merged exposition) + ``/fleet/snapshots``
    (raw worker docs) over ``store`` on a daemon ThreadingHTTPServer.
    Returns ``(httpd, thread, bound_port)``. The process's own ``/metrics``
    and ``/healthz`` come along from the shared handler."""
    from urllib.parse import urlparse

    from deeplearning4j_tpu.serve import httpcommon

    collector = FleetCollector(store)

    class FleetHandler(httpcommon.ObservedHandler):
        inflight = httpcommon.InFlight()

        def handle_get(self) -> int:
            path = urlparse(self.path).path
            if path == "/fleet/metrics":
                return self.send_body(
                    200, collector.prometheus_text().encode("utf-8"),
                    httpcommon.PROM_CTYPE)
            if path == "/fleet/snapshots":
                return self.send_json(
                    200, {"snapshots": collector.collect_snapshots()})
            self.send_response(404)
            self.end_headers()
            return 404

    return httpcommon.start_server(FleetHandler, port)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.obs.fleet",
        description="Fleet metrics collector over an elastic store "
                    "(FileStore dir or tcp://host:port netstore)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    srv = sub.add_parser("serve", help="HTTP collector: /fleet/metrics + "
                                       "/fleet/snapshots")
    srv.add_argument("--store", required=True)
    srv.add_argument("--port", type=int, default=0)
    rnd = sub.add_parser("render", help="print the merged exposition once")
    rnd.add_argument("--store", required=True)
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.parallel.netstore import open_store

    store = open_store(args.store)
    if args.cmd == "render":
        sys.stdout.write(FleetCollector(store).prometheus_text())
        return 0
    httpd, thread, bound = serve_collector(store, port=args.port)
    print(json.dumps({"port": bound}), flush=True)
    try:
        thread.join()
    except KeyboardInterrupt:  # pragma: no cover - interactive stop
        httpd.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI shim
    raise SystemExit(main())
