"""Structured JSONL event log with rotation and never-crash discipline.

One line per event::

    {"ts": 1754400000.123, "kind": "checkpoint_saved", "host": "...",
     "pid": 1234, "perf_s": 12.345, "path": "...", ...}

``ts`` is intentionally wall-clock (log lines are correlated with external
systems); all DURATION fields are computed by callers from monotonic clocks.
Every line is also stamped by ``obs/fleet.py`` with ``host``/``pid``/
``perf_s`` — the (ts, perf_s) pair on each line is a wall↔perf anchor, so
merging logs from many hosts never relies on synchronized wall clocks —
plus ``rank``/``inc`` when an elastic process context is set and
``trace_id`` when emitted inside a request's trace scope.
Telemetry must never take training down — same discipline as
``ui/storage.py``'s remote router: serialization falls back to ``str()``,
any I/O error drops the event (counted in ``dl4j_events_dropped_total``)
and the log keeps running.

Rotation: when the active file exceeds ``max_bytes`` it is renamed to
``<path>.1`` (replacing any previous rollover) and a fresh file is started,
bounding disk use at ~2x ``max_bytes``.

Enabling: ``obs.configure_event_log(path)`` explicitly, or set
``DL4J_TPU_EVENT_LOG=<path>`` before the first event (checked lazily per
emit, per the repo's read-env-per-call convention). Every event also
increments ``dl4j_events_total{kind=...}`` whether or not a file sink is
configured, so event counts are scrapeable at /metrics regardless.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from deeplearning4j_tpu.obs import fleet, metrics

__all__ = ["EventLog", "event_log"]

_DEFAULT_MAX_BYTES = 4 * 1024 * 1024


class EventLog:
    def __init__(self, reg: Optional[metrics.MetricsRegistry] = None):
        self._reg = reg or metrics.registry()
        self._counts = self._reg.counter(
            "dl4j_events_total", "structured events by kind", ("kind",))
        self._dropped = self._reg.counter(
            "dl4j_events_dropped_total",
            "events lost to serialization/I-O errors (never-crash discipline)")
        self._lock = threading.Lock()
        self._path: Optional[str] = None
        self._max_bytes = _DEFAULT_MAX_BYTES
        self._size = 0
        self._env_checked = False

    # -- configuration -----------------------------------------------------

    def configure(self, path: Optional[str], max_bytes: int = _DEFAULT_MAX_BYTES):
        """Point the file sink at ``path`` (None disables it). Counting via
        the registry continues either way."""
        with self._lock:
            self._path = str(path) if path else None
            self._max_bytes = max(1024, int(max_bytes))
            self._size = self._current_size()
            self._env_checked = True  # explicit config wins over the env knob

    def _current_size(self) -> int:
        if not self._path:
            return 0
        try:
            return os.path.getsize(self._path)
        except OSError:
            return 0

    def _maybe_adopt_env(self):
        # lazy: picked up on first emit so subprocesses (bench isolation,
        # chaos smoke) inherit the knob without an explicit configure call
        if self._env_checked:
            return
        self._env_checked = True
        path = os.environ.get("DL4J_TPU_EVENT_LOG")
        if path:
            self._path = path
            try:
                mb = int(os.environ.get("DL4J_TPU_EVENT_LOG_MAX_BYTES", "0"))
            except ValueError:
                mb = 0
            if mb > 0:
                self._max_bytes = max(1024, mb)
            self._size = self._current_size()

    @property
    def path(self) -> Optional[str]:
        with self._lock:
            self._maybe_adopt_env()
            return self._path

    # -- emission ----------------------------------------------------------

    def emit(self, kind: str, **fields):
        """Record one event. Never raises."""
        try:
            self._counts.inc(kind=kind)
            with self._lock:
                self._maybe_adopt_env()
                if not self._path:
                    return
                rec = {"ts": time.time(), "kind": kind}  # graftlint: disable=jit-purity
                rec.update(fields)
                fleet.stamp_event(rec)
                try:
                    line = json.dumps(rec, default=str)
                except (TypeError, ValueError):
                    line = json.dumps({"ts": rec["ts"], "kind": kind,
                                       "error": "unserializable-event"})
                data = line + "\n"
                if self._size + len(data) > self._max_bytes:
                    self._rotate()
                with open(self._path, "a", encoding="utf-8") as fh:
                    fh.write(data)
                self._size += len(data)
        except Exception:
            try:
                self._dropped.inc()
            except Exception:
                pass

    def _rotate(self):
        # caller holds the lock
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass
        self._size = 0

    # -- views -------------------------------------------------------------

    def counts(self) -> dict:
        """{kind: count} since process start (or last obs.reset())."""
        return {k[0]: v for k, v in self._counts.as_dict().items()}


_LOG = EventLog()


def event_log() -> EventLog:
    return _LOG
