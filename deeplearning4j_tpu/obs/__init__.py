"""Unified observability layer: metrics registry, span tracer, event log.

The four disjoint telemetry islands that grew across PRs 1-4 — bucketing
counters (utils/bucketing.py), comm bytes (parallel/grads.py), guard events
(train/resilience.py) and listener throughput (train/listeners.py) — all
land in ONE process-wide metrics registry, queryable three ways:

- ``obs.snapshot()``      JSON dict (embedded in bench.py results and the
                          resilience checkpoint telemetry field)
- ``/metrics``            Prometheus text exposition on the UI server
- ``obs.recent_spans()``  ring buffer of recent step spans

Public surface::

    obs.counter/gauge/histogram(name, help, label_names)  # get-or-create
    with obs.span("mln.fit_batch"): ...                   # wall+cpu windows
    obs.event("checkpoint_saved", path=..., crc=...)      # JSONL + counter
    obs.configure_event_log(path)                         # or DL4J_TPU_EVENT_LOG
    obs.snapshot(); obs.prometheus_text(); obs.reset()

Hot-path discipline: recording is host-side dict updates under locks; no
jax import, no device sync, ``block_until_ready`` never called. Set
``DL4J_TPU_OBS=0`` to disable span recording and event emission (counter
shims underneath ``bucketing.telemetry()`` stay live — they ARE the
storage); the overhead of the full layer is benched by the ``mnist_mlp``
arm in bench.py (gate: <= 2%).
"""

from __future__ import annotations

import os
from typing import Optional

from deeplearning4j_tpu.obs import events as _events
from deeplearning4j_tpu.obs import metrics as _metrics
from deeplearning4j_tpu.obs import spans as _spans

__all__ = [
    "compile_span",
    "configure_event_log",
    "cost_report",
    "counter",
    "current_trace",
    "enabled",
    "event",
    "event_log",
    "gauge",
    "histogram",
    "process_context",
    "publish_snapshot",
    "set_process_context",
    "trace_scope",
    "observe_itl",
    "observe_request",
    "observe_shed",
    "observe_ttft",
    "set_decode_occupancy",
    "phase_spans_enabled",
    "prometheus_text",
    "recent_spans",
    "registry",
    "reset",
    "save_spans",
    "snapshot",
    "span",
    "tracer",
]


def enabled() -> bool:
    """Master switch (default on). Read per call so tests can flip it."""
    return os.environ.get("DL4J_TPU_OBS", "1") != "0"


def phase_spans_enabled() -> bool:
    """Opt-in split-dispatch profiling mode (DL4J_TPU_PHASE_SPANS=1): the
    fit loops dispatch fwd/bwd/update as separate blocked executables so
    nested phase spans carry real per-phase wall time. Costs pipeline
    overlap — a profiling mode, never the default. Implies enabled()."""
    return enabled() and os.environ.get("DL4J_TPU_PHASE_SPANS", "0") == "1"


# -- metrics ----------------------------------------------------------------

def registry() -> _metrics.MetricsRegistry:
    return _metrics.registry()


def counter(name: str, help: str = "", label_names=()) -> _metrics.Counter:
    return _metrics.registry().counter(name, help, label_names)


def gauge(name: str, help: str = "", label_names=()) -> _metrics.Gauge:
    return _metrics.registry().gauge(name, help, label_names)


def histogram(name: str, help: str = "", label_names=()) -> _metrics.Histogram:
    return _metrics.registry().histogram(name, help, label_names)


def prometheus_text() -> str:
    # exposition is report-time: resolve pending lazy cost signatures first
    # so the XLA cost / MFU gauges reflect every compile seen so far
    try:
        from deeplearning4j_tpu.obs import profile as _profile

        _profile.snapshot()
    except Exception:
        pass
    return _metrics.registry().prometheus_text()


# -- spans ------------------------------------------------------------------

def tracer() -> _spans.SpanTracer:
    return _spans.tracer()


def span(name: str, **attrs):
    """``with obs.span("mln.fit_batch"): ...`` — see obs/spans.py."""
    return _spans.tracer().span(name, **attrs)


def compile_span(site: str, **attrs):
    """``with obs.compile_span("mln.step"): ...`` — the ``compile`` span
    kind aggregating all XLA compilation work (see obs/spans.py)."""
    return _spans.compile_span(site, **attrs)


def recent_spans(n: Optional[int] = None):
    return _spans.tracer().recent(n)


def save_spans(path: str) -> int:
    """Dump the span ring + timeline anchor as JSON for offline trace
    export (also available via DL4J_TPU_SPAN_DUMP at exit)."""
    return _spans.tracer().dump(path)


# -- profiling / SLOs -------------------------------------------------------

def cost_report(resolve: bool = True) -> dict:
    """XLA static costs + roofline utilization (see obs/profile.py).
    Report-time only — resolution may lower pending lazy signatures."""
    from deeplearning4j_tpu.obs import profile as _profile

    return _profile.cost_report(resolve=resolve)


def observe_request(route: str, latency_s: float, status: str = "ok",
                    error: bool = False):
    """Record one serving/HTTP request against the SLO tracker
    (see obs/slo.py). No-op when DL4J_TPU_OBS=0; never raises."""
    from deeplearning4j_tpu.obs import slo as _slo

    _slo.observe_request(route, latency_s, status=status, error=error)


def observe_shed(route: str, reason: str = "backpressure"):
    """Record one load-shedding decision against the SLO tracker
    (see obs/slo.py). No-op when DL4J_TPU_OBS=0; never raises."""
    from deeplearning4j_tpu.obs import slo as _slo

    _slo.observe_shed(route, reason=reason)


def observe_ttft(route: str, latency_s: float):
    """Record one stream's time-to-first-token (see obs/slo.py).
    No-op when DL4J_TPU_OBS=0; never raises."""
    from deeplearning4j_tpu.obs import slo as _slo

    _slo.observe_ttft(route, latency_s)


def observe_itl(route: str, latency_s: float):
    """Record one inter-token latency gap (see obs/slo.py).
    No-op when DL4J_TPU_OBS=0; never raises."""
    from deeplearning4j_tpu.obs import slo as _slo

    _slo.observe_itl(route, latency_s)


def set_decode_occupancy(model: str, streams: int):
    """Set the decode-batch occupancy gauge (see obs/slo.py).
    No-op when DL4J_TPU_OBS=0; never raises."""
    from deeplearning4j_tpu.obs import slo as _slo

    _slo.set_decode_occupancy(model, streams)


# -- fleet (cross-process: trace context, federation) -----------------------

def current_trace():
    """The thread's active W3C trace context, or None (see obs/fleet.py)."""
    from deeplearning4j_tpu.obs import fleet as _fleet

    return _fleet.current_trace()


def trace_scope(ctx):
    """``with obs.trace_scope(ctx): ...`` — spans/events recorded inside
    carry ``ctx``'s trace/span ids (see obs/fleet.py)."""
    from deeplearning4j_tpu.obs import fleet as _fleet

    return _fleet.trace_scope(ctx)


def set_process_context(**fields):
    """Tag this process's spans/events with rank/wid/incarnation/slice
    (see obs/fleet.py)."""
    from deeplearning4j_tpu.obs import fleet as _fleet

    _fleet.set_process_context(**fields)


def process_context() -> dict:
    """host/pid plus any identity set via ``set_process_context``."""
    from deeplearning4j_tpu.obs import fleet as _fleet

    return _fleet.process_context()


def publish_snapshot(store, wid: str, extra: Optional[dict] = None) -> str:
    """Publish this process's metrics into the elastic store for the fleet
    collector (see obs/fleet.py). Report-time only — never call from
    traced/per-batch code."""
    from deeplearning4j_tpu.obs import fleet as _fleet

    return _fleet.publish_snapshot(store, wid, extra=extra)


# -- events -----------------------------------------------------------------

def event_log() -> _events.EventLog:
    return _events.event_log()


def event(kind: str, **fields):
    """Emit one structured event (no-op when DL4J_TPU_OBS=0; never raises)."""
    if enabled():
        _events.event_log().emit(kind, **fields)


def configure_event_log(path: Optional[str], max_bytes: int = 4 * 1024 * 1024):
    _events.event_log().configure(path, max_bytes)


# -- aggregate views --------------------------------------------------------

def snapshot() -> dict:
    """JSON-friendly aggregate of everything the registry knows: metric
    families (counters/gauges plain, histograms summarized), per-span
    aggregates, and event counts. Embedded in bench.py result JSON and in
    the resilience checkpoint telemetry field (round-trips through JSON)."""
    from deeplearning4j_tpu.obs import profile as _profile
    from deeplearning4j_tpu.utils import bucketing

    return {
        "metrics": _metrics.registry().snapshot(),
        "spans": _spans.tracer().summary(),
        "events": _events.event_log().counts(),
        "bucketing": bucketing.telemetry().snapshot(),
        "profile": _profile.snapshot(),
    }


def reset():
    """Zero every metric series, drop recent spans and the cost ledger,
    keep configuration (event-log path, family registrations). Tests and
    bench isolation."""
    from deeplearning4j_tpu.obs import profile as _profile
    from deeplearning4j_tpu.obs import slo as _slo

    _metrics.registry().reset()
    _spans.tracer().clear()
    _profile.reset()
    _slo._reset_tracker()
