"""Span tracer: host wall-time + dispatch-time windows around step-level work.

``span("mln.fit_batch")`` wraps one unit of work. On exit it records

- ``wall_s``  — host wall-clock window (``time.perf_counter`` delta). With
  async dispatch this includes any time the host BLOCKED on the device
  (donated-buffer back-pressure, explicit syncs in callers) but never forces
  a sync itself — ``block_until_ready`` is deliberately absent here.
- ``cpu_s``   — the dispatch-time window: CPU time this thread spent inside
  the span (``time.thread_time`` delta). For a healthy async pipeline
  ``cpu_s`` ≈ tracing/dispatch cost and ``wall_s`` ≫ ``cpu_s`` means the
  host was waiting (device-bound or back-pressured) — the two windows
  together locate the bottleneck without device instrumentation.

Nesting is tracked per thread: a span opened while another is active records
the outer span's name as ``parent`` and its own ``depth``. Finished spans go
to a bounded ring buffer (most recent last) and into the
``dl4j_span_seconds`` histogram family in the metrics registry.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.obs import metrics

__all__ = ["SpanTracer", "compile_span", "tracer"]

_RING = 512  # finished spans retained


class _ActiveSpan:
    __slots__ = ("name", "attrs", "t0", "c0")

    def __init__(self, name: str, attrs: Dict[str, object]):
        self.name = name
        self.attrs = attrs
        self.t0 = time.perf_counter()
        self.c0 = time.thread_time()


class _SpanContext:
    """Context manager handed out by ``SpanTracer.span``. Re-entrant-safe in
    the sense that each ``with`` creates a fresh context."""

    __slots__ = ("_tracer", "_name", "_attrs", "_active")

    def __init__(self, tracer: "SpanTracer", name: str, attrs: Dict[str, object]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._active: Optional[_ActiveSpan] = None

    def __enter__(self):
        self._active = self._tracer._push(self._name, self._attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._pop(self._active, error=exc_type is not None)
        return False


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL = _NullContext()


class SpanTracer:
    def __init__(self, reg: Optional[metrics.MetricsRegistry] = None):
        self._reg = reg or metrics.registry()
        self._hist = self._reg.histogram(
            "dl4j_span_seconds",
            "host wall-time of instrumented spans (see dl4j_span_cpu_seconds "
            "for the dispatch-time window)", ("span",))
        self._cpu = self._reg.histogram(
            "dl4j_span_cpu_seconds",
            "thread CPU time inside instrumented spans (dispatch cost; "
            "wall >> cpu means the host was waiting)", ("span",))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=_RING)
        self._tls = threading.local()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **attrs) -> object:
        """Context manager timing one unit of work. With observability
        disabled (DL4J_TPU_OBS=0) returns a shared no-op context."""
        from deeplearning4j_tpu import obs

        if not obs.enabled():
            return _NULL
        return _SpanContext(self, name, attrs)

    def _stack(self) -> List[_ActiveSpan]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, name: str, attrs: Dict[str, object]) -> _ActiveSpan:
        sp = _ActiveSpan(name, attrs)
        self._stack().append(sp)
        return sp

    def _pop(self, sp: Optional[_ActiveSpan], error: bool = False):
        if sp is None:
            return
        wall = time.perf_counter() - sp.t0
        cpu = time.thread_time() - sp.c0
        stack = self._stack()
        # tolerate exotic unwinds: pop through to OUR frame
        while stack and stack[-1] is not sp:
            stack.pop()
        if stack:
            stack.pop()
        parent = stack[-1].name if stack else None
        rec = {
            "span": sp.name,
            "wall_s": wall,
            "cpu_s": cpu,
            "parent": parent,
            "depth": len(stack),
        }
        if error:
            rec["error"] = True
        if sp.attrs:
            rec["attrs"] = sp.attrs
        with self._lock:
            self._ring.append(rec)
        self._hist.observe(wall, span=sp.name)
        self._cpu.observe(cpu, span=sp.name)

    # -- views -------------------------------------------------------------

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """Most recent finished spans, oldest first."""
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def summary(self) -> Dict[str, dict]:
        """Per-span-name {count, wall_sum_s, wall_p50_s, wall_max_s, cpu_sum_s}
        from the registry histograms (JSON-friendly, for ``obs.snapshot()``)."""
        out: Dict[str, dict] = {}
        for key, _ in self._hist.series():
            name = key[0]
            s = self._hist.summary(span=name)
            c = self._cpu.summary(span=name)
            out[name] = {
                "count": s["count"],
                "wall_sum_s": s["sum"],
                "wall_p50_s": s["p50"],
                "wall_max_s": s["max"],
                "cpu_sum_s": c["sum"] if c else 0.0,
            }
        return out

    def clear(self):
        with self._lock:
            self._ring.clear()


_TRACER = SpanTracer()


def tracer() -> SpanTracer:
    return _TRACER


def compile_span(site: str, **attrs):
    """The ``compile`` span kind: one span family for all XLA compilation
    work — AOT warmup (``nn/aot.py``), lazy jit traces instrumented by
    callers, bundle re-validation. The jitted site rides as an attribute so
    every compile aggregates under the single ``compile`` series: its
    ``wall_sum_s`` in ``obs.snapshot()`` IS the process's total compile
    cost, the number the cold_start bench drives down."""
    return tracer().span("compile", site=site, **attrs)
