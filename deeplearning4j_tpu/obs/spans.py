"""Span tracer: host wall-time + dispatch-time windows around step-level work.

``span("mln.fit_batch")`` wraps one unit of work. On exit it records

- ``wall_s``  — host wall-clock window (``time.perf_counter`` delta). With
  async dispatch this includes any time the host BLOCKED on the device
  (donated-buffer back-pressure, explicit syncs in callers) but never forces
  a sync itself — ``block_until_ready`` is deliberately absent here.
- ``cpu_s``   — the dispatch-time window: CPU time this thread spent inside
  the span (``time.thread_time`` delta). For a healthy async pipeline
  ``cpu_s`` ≈ tracing/dispatch cost and ``wall_s`` ≫ ``cpu_s`` means the
  host was waiting (device-bound or back-pressured) — the two windows
  together locate the bottleneck without device instrumentation.

Nesting is tracked per thread: a span opened while another is active records
the outer span's name as ``parent`` and its own ``depth``. Finished spans go
to a bounded ring buffer (most recent last) and into the
``dl4j_span_seconds`` histogram family in the metrics registry.

Ring records carry everything ``obs/trace_export.py`` needs to render a
Chrome/Perfetto timeline: ``t0_s`` (span start on the process-local
``perf_counter`` timeline), ``tid``/``thread`` (OS thread identity for
per-thread lanes), and the tracer's ``anchor()`` maps that timeline onto
wall-clock so event-log instants (whose ``ts`` is wall-clock by design)
land on the same axis.

Ring capacity defaults to 512 finished spans and is tunable via
``DL4J_TPU_SPAN_RING`` (read at tracer construction, i.e. first import of
the obs layer). Overflow is NOT silent: every record evicted to make room
increments ``dl4j_spans_dropped_total`` — mirroring the
``dl4j_events_dropped_total`` discipline — so a long fit that outruns the
ring is visible in /metrics instead of producing quietly truncated traces.
``DL4J_TPU_SPAN_DUMP=<path>`` dumps the ring (plus the anchor) as JSON at
interpreter exit for offline trace export.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.obs import fleet, metrics

__all__ = ["SpanTracer", "compile_span", "tracer"]

_RING_DEFAULT = 512  # finished spans retained unless DL4J_TPU_SPAN_RING


def _ring_capacity() -> int:
    raw = os.environ.get("DL4J_TPU_SPAN_RING", "")
    try:
        n = int(raw)
    except ValueError:
        return _RING_DEFAULT
    return n if n > 0 else _RING_DEFAULT


class _ActiveSpan:
    __slots__ = ("name", "attrs", "t0", "c0")

    def __init__(self, name: str, attrs: Dict[str, object]):
        self.name = name
        self.attrs = attrs
        self.t0 = time.perf_counter()
        self.c0 = time.thread_time()


class _SpanContext:
    """Context manager handed out by ``SpanTracer.span``. Re-entrant-safe in
    the sense that each ``with`` creates a fresh context."""

    __slots__ = ("_tracer", "_name", "_attrs", "_active")

    def __init__(self, tracer: "SpanTracer", name: str, attrs: Dict[str, object]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._active: Optional[_ActiveSpan] = None

    def __enter__(self):
        self._active = self._tracer._push(self._name, self._attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._pop(self._active, error=exc_type is not None)
        return False


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL = _NullContext()


class SpanTracer:
    def __init__(self, reg: Optional[metrics.MetricsRegistry] = None,
                 ring_size: Optional[int] = None):
        self._reg = reg or metrics.registry()
        self._hist = self._reg.histogram(
            "dl4j_span_seconds",
            "host wall-time of instrumented spans (see dl4j_span_cpu_seconds "
            "for the dispatch-time window)", ("span",))
        self._cpu = self._reg.histogram(
            "dl4j_span_cpu_seconds",
            "thread CPU time inside instrumented spans (dispatch cost; "
            "wall >> cpu means the host was waiting)", ("span",))
        self._dropped = self._reg.counter(
            "dl4j_spans_dropped_total",
            "finished spans evicted from the bounded span ring "
            "(raise DL4J_TPU_SPAN_RING if this grows during a window "
            "you want to trace)")
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_size or _ring_capacity())
        self._tls = threading.local()
        # One (wall-clock, perf_counter) pair sampled back to back: maps the
        # perf_counter timeline every span uses onto wall-clock so trace
        # export can align event-log instants (wall-clock ts) with spans.
        self._anchor = {"wall_s": time.time(), "perf_s": time.perf_counter()}

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **attrs) -> object:
        """Context manager timing one unit of work. With observability
        disabled (DL4J_TPU_OBS=0) returns a shared no-op context."""
        from deeplearning4j_tpu import obs

        if not obs.enabled():
            return _NULL
        return _SpanContext(self, name, attrs)

    def _stack(self) -> List[_ActiveSpan]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, name: str, attrs: Dict[str, object]) -> _ActiveSpan:
        sp = _ActiveSpan(name, attrs)
        self._stack().append(sp)
        return sp

    def _pop(self, sp: Optional[_ActiveSpan], error: bool = False):
        if sp is None:
            return
        wall = time.perf_counter() - sp.t0
        cpu = time.thread_time() - sp.c0
        stack = self._stack()
        # tolerate exotic unwinds: pop through to OUR frame
        while stack and stack[-1] is not sp:
            stack.pop()
        if stack:
            stack.pop()
        parent = stack[-1].name if stack else None
        th = threading.current_thread()
        rec = {
            "span": sp.name,
            "t0_s": sp.t0,
            "wall_s": wall,
            "cpu_s": cpu,
            "parent": parent,
            "depth": len(stack),
            "tid": th.ident,
            "thread": th.name,
        }
        if error:
            rec["error"] = True
        if sp.attrs:
            rec["attrs"] = sp.attrs
        # rank/incarnation + active trace ids (obs/fleet.py) — cheap dict
        # writes; records keep the rank current when they were recorded,
        # which matters across elastic reforms
        fleet.stamp_span(rec)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped.inc()
            self._ring.append(rec)
        self._hist.observe(wall, span=sp.name)
        self._cpu.observe(cpu, span=sp.name)

    # -- views -------------------------------------------------------------

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """Most recent finished spans, oldest first."""
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def anchor(self) -> Dict[str, float]:
        """The (wall_s, perf_s) pair mapping the span timeline to wall-clock:
        ``wall = anchor.wall_s + (t0_s - anchor.perf_s)``."""
        return dict(self._anchor)

    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def summary(self) -> Dict[str, dict]:
        """Per-span-name {count, wall_sum_s, wall_p50_s, wall_max_s, cpu_sum_s}
        from the registry histograms (JSON-friendly, for ``obs.snapshot()``)."""
        out: Dict[str, dict] = {}
        for key, _ in self._hist.series():
            name = key[0]
            s = self._hist.summary(span=name)
            c = self._cpu.summary(span=name)
            out[name] = {
                "count": s["count"],
                "wall_sum_s": s["sum"],
                "wall_p50_s": s["p50"],
                "wall_max_s": s["max"],
                "cpu_sum_s": c["sum"] if c else 0.0,
            }
        return out

    def dump(self, path: str) -> int:
        """Write the ring + anchor as JSON for offline trace export
        (``python -m deeplearning4j_tpu.obs.trace_export --spans <path>``).
        Returns the number of spans written."""
        spans = self.recent()
        doc = {"anchor": self.anchor(), "spans": spans,
               "process": fleet.process_context()}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(spans)

    def clear(self):
        with self._lock:
            self._ring.clear()


_TRACER = SpanTracer()


def tracer() -> SpanTracer:
    return _TRACER


def _dump_at_exit():
    path = os.environ.get("DL4J_TPU_SPAN_DUMP")
    if not path:
        return
    try:
        _TRACER.dump(path)
    except OSError:
        pass  # exit-time best effort; never mask the real exit status


atexit.register(_dump_at_exit)


def compile_span(site: str, **attrs):
    """The ``compile`` span kind: one span family for all XLA compilation
    work — AOT warmup (``nn/aot.py``), lazy jit traces instrumented by
    callers, bundle re-validation. The jitted site rides as an attribute so
    every compile aggregates under the single ``compile`` series: its
    ``wall_sum_s`` in ``obs.snapshot()`` IS the process's total compile
    cost, the number the cold_start bench drives down."""
    return tracer().span("compile", site=site, **attrs)
