"""DataVec-style transform pipeline: Schema + TransformProcess.

Capability parity with the DataVec ETL layer the reference consumes
(deeplearning4j-core/src/main/java/org/deeplearning4j/datasets/datavec/
RecordReaderDataSetIterator.java pulls records through DataVec's
Schema/TransformProcess; DataVec itself lives in its own repo). The surface
mirrors DataVec's: a Schema describes typed columns, a TransformProcess is
an ordered list of serializable column operations whose output schema is
derivable WITHOUT data, and an executor applies them to records.

TPU-first redesign: operations are COLUMNAR numpy transforms (vectorized
over the whole record batch), not per-record Writable visitors — the
pipeline output feeds jnp.asarray directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

COLUMN_TYPES = ("double", "integer", "categorical", "string", "time")


@dataclass(frozen=True)
class ColumnMeta:
    name: str
    kind: str
    categories: Tuple[str, ...] = ()   # categorical only

    def __post_init__(self):
        if self.kind not in COLUMN_TYPES:
            raise ValueError(f"unknown column type {self.kind!r}")


class Schema:
    """Typed column layout (datavec Schema). Build via the fluent builder::

        schema = (Schema.builder()
                  .add_double("sepal_len")
                  .add_categorical("species", ["a", "b", "c"])
                  .build())
    """

    def __init__(self, columns: Sequence[ColumnMeta]):
        names = [c.name for c in columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        self.columns: Tuple[ColumnMeta, ...] = tuple(columns)

    # -- builder -----------------------------------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_double(self, *names: str) -> "Schema.Builder":
            self._cols += [ColumnMeta(n, "double") for n in names]
            return self

        def add_integer(self, *names: str) -> "Schema.Builder":
            self._cols += [ColumnMeta(n, "integer") for n in names]
            return self

        def add_string(self, *names: str) -> "Schema.Builder":
            self._cols += [ColumnMeta(n, "string") for n in names]
            return self

        def add_time(self, *names: str) -> "Schema.Builder":
            self._cols += [ColumnMeta(n, "time") for n in names]
            return self

        def add_categorical(self, name: str, categories: Sequence[str]) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, "categorical", tuple(categories)))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    # -- queries -----------------------------------------------------------
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column {name!r}; have {self.names()}")

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self.index_of(name)]

    def __len__(self):
        return len(self.columns)

    def __eq__(self, other):
        return isinstance(other, Schema) and self.columns == other.columns

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        return {"columns": [
            {"name": c.name, "kind": c.kind,
             **({"categories": list(c.categories)} if c.categories else {})}
            for c in self.columns]}

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        return Schema([ColumnMeta(c["name"], c["kind"],
                                  tuple(c.get("categories", ())))
                       for c in d["columns"]])


# ---------------------------------------------------------------------------
# Operations: schema_out(schema) derives the output schema WITHOUT data;
# apply(columns, schema) transforms the columnar dict
# ---------------------------------------------------------------------------

_OPS: Dict[str, type] = {}


def _register_op(name):
    def deco(cls):
        cls.OP = name
        _OPS[name] = cls
        return cls
    return deco


@dataclass
class _Op:
    def schema_out(self, schema: Schema) -> Schema:
        return schema

    def apply(self, cols: Dict[str, np.ndarray], schema: Schema) -> Dict[str, np.ndarray]:
        return cols

    def to_dict(self) -> dict:
        # the type tag lives under "transform", NOT "op" — DoubleMathOp has
        # an instance field named "op" that must round-trip untouched
        d = {"transform": type(self).OP}
        d.update({k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in self.__dict__.items()})
        return d


@_register_op("remove_columns")
@dataclass
class RemoveColumns(_Op):
    names: Tuple[str, ...] = ()

    def schema_out(self, schema):
        for n in self.names:
            schema.index_of(n)  # validate
        return Schema([c for c in schema.columns if c.name not in self.names])

    def apply(self, cols, schema):
        return {k: v for k, v in cols.items() if k not in self.names}


@_register_op("keep_columns")
@dataclass
class KeepColumns(_Op):
    names: Tuple[str, ...] = ()

    def schema_out(self, schema):
        return Schema([schema.column(n) for n in self.names])

    def apply(self, cols, schema):
        return {n: cols[n] for n in self.names}


@_register_op("rename_column")
@dataclass
class RenameColumn(_Op):
    old: str = ""
    new: str = ""

    def schema_out(self, schema):
        schema.index_of(self.old)  # validate: a typo'd rename must not no-op
        return Schema([
            ColumnMeta(self.new, c.kind, c.categories) if c.name == self.old else c
            for c in schema.columns])

    def apply(self, cols, schema):
        return {self.new if k == self.old else k: v for k, v in cols.items()}


@_register_op("categorical_to_integer")
@dataclass
class CategoricalToInteger(_Op):
    name: str = ""

    def schema_out(self, schema):
        c = schema.column(self.name)
        if c.kind != "categorical":
            raise ValueError(f"{self.name} is {c.kind}, not categorical")
        return Schema([ColumnMeta(x.name, "integer") if x.name == self.name else x
                       for x in schema.columns])

    def apply(self, cols, schema):
        cats = list(schema.column(self.name).categories)
        lut = {c: i for i, c in enumerate(cats)}
        vals = cols[self.name]
        try:
            out = np.asarray([lut[str(v)] for v in vals], np.int64)
        except KeyError as e:
            raise ValueError(f"value {e} not in categories {cats}") from None
        new = dict(cols)
        new[self.name] = out
        return new


@_register_op("categorical_to_one_hot")
@dataclass
class CategoricalToOneHot(_Op):
    name: str = ""

    def schema_out(self, schema):
        c = schema.column(self.name)
        if c.kind != "categorical":
            raise ValueError(f"{self.name} is {c.kind}, not categorical")
        out = []
        for x in schema.columns:
            if x.name == self.name:
                out += [ColumnMeta(f"{self.name}[{cat}]", "double")
                        for cat in c.categories]
            else:
                out.append(x)
        return Schema(out)

    def apply(self, cols, schema):
        cats = list(schema.column(self.name).categories)
        lut = {c: i for i, c in enumerate(cats)}
        try:
            idx = np.asarray([lut[str(v)] for v in cols[self.name]], np.int64)
        except KeyError as e:
            raise ValueError(
                f"column {self.name!r}: value {e} not in categories {cats}"
            ) from None
        eye = np.eye(len(cats), dtype=np.float64)[idx]
        out = {}
        for k, v in cols.items():
            if k == self.name:
                for j, cat in enumerate(cats):
                    out[f"{self.name}[{cat}]"] = eye[:, j]
            else:
                out[k] = v
        return out


@_register_op("string_to_categorical")
@dataclass
class StringToCategorical(_Op):
    name: str = ""
    categories: Tuple[str, ...] = ()

    def schema_out(self, schema):
        c = schema.column(self.name)
        if c.kind != "string":
            raise ValueError(f"{self.name} is {c.kind}, not string")
        return Schema([ColumnMeta(x.name, "categorical", tuple(self.categories))
                       if x.name == self.name else x for x in schema.columns])


_MATH = {
    "add": np.add, "subtract": np.subtract, "multiply": np.multiply,
    "divide": np.divide, "power": np.power, "modulus": np.mod,
}


@_register_op("double_math")
@dataclass
class DoubleMathOp(_Op):
    name: str = ""
    op: str = "add"
    scalar: float = 0.0

    def schema_out(self, schema):
        c = schema.column(self.name)
        if c.kind not in ("double", "integer"):
            raise ValueError(f"{self.name} is {c.kind}, not numeric")
        if self.op not in _MATH:
            raise ValueError(f"unknown math op {self.op!r}; have {sorted(_MATH)}")
        return schema

    def apply(self, cols, schema):
        new = dict(cols)
        new[self.name] = _MATH[self.op](
            np.asarray(cols[self.name], np.float64), self.scalar)
        return new


@_register_op("normalize_min_max")
@dataclass
class NormalizeMinMax(_Op):
    """(x - min) / (max - min) with STATED stats (DataVec derives them from
    an analysis pass; pass them explicitly here — data-free schema
    derivation is preserved)."""

    name: str = ""
    min: float = 0.0
    max: float = 1.0

    def schema_out(self, schema):
        if schema.column(self.name).kind not in ("double", "integer"):
            raise ValueError(f"{self.name} is not numeric")
        if self.max <= self.min:
            raise ValueError("max must exceed min")
        return schema

    def apply(self, cols, schema):
        new = dict(cols)
        x = np.asarray(cols[self.name], np.float64)
        new[self.name] = (x - self.min) / (self.max - self.min)
        return new


@_register_op("filter_numeric")
@dataclass
class FilterNumericCondition(_Op):
    """Drop ROWS where the condition holds (datavec ConditionFilter):
    condition in <, <=, >, >=, ==, != against a scalar."""

    name: str = ""
    condition: str = "<"
    value: float = 0.0

    _CMP = {"<": np.less, "<=": np.less_equal, ">": np.greater,
            ">=": np.greater_equal, "==": np.equal, "!=": np.not_equal}

    def schema_out(self, schema):
        if schema.column(self.name).kind not in ("double", "integer"):
            raise ValueError(f"{self.name} is not numeric")
        if self.condition not in self._CMP:
            raise ValueError(f"unknown condition {self.condition!r}")
        return schema

    def apply(self, cols, schema):
        x = np.asarray(cols[self.name], np.float64)
        drop = self._CMP[self.condition](x, self.value)
        keep = ~drop
        return {k: np.asarray(v)[keep] for k, v in cols.items()}


@_register_op("replace_invalid")
@dataclass
class ReplaceInvalidWithValue(_Op):
    """NaN/inf in a numeric column -> value (ReplaceInvalidWithIntegerTransform
    family)."""

    name: str = ""
    value: float = 0.0

    def schema_out(self, schema):
        if schema.column(self.name).kind not in ("double", "integer"):
            raise ValueError(f"{self.name} is not numeric")
        return schema

    def apply(self, cols, schema):
        new = dict(cols)
        x = np.asarray(cols[self.name], np.float64)
        new[self.name] = np.where(np.isfinite(x), x, self.value)
        return new


# ---------------------------------------------------------------------------
# TransformProcess
# ---------------------------------------------------------------------------


class TransformProcess:
    """Ordered, serializable column transforms (datavec TransformProcess).

    ``final_schema`` is derived without data; ``execute`` runs the columnar
    pipeline over records (list of rows, or a columnar dict)."""

    def __init__(self, initial_schema: Schema, ops: Sequence[_Op]):
        self.initial_schema = initial_schema
        self.ops = list(ops)
        # validate the whole chain up front (schema derivation is data-free)
        s = initial_schema
        self._schemas = [s]
        for op in self.ops:
            s = op.schema_out(s)
            self._schemas.append(s)

    def final_schema(self) -> Schema:
        return self._schemas[-1]

    # -- builder -----------------------------------------------------------
    class Builder:
        def __init__(self, schema: Schema):
            self.schema = schema
            self.ops: List[_Op] = []

        def remove_columns(self, *names):
            self.ops.append(RemoveColumns(tuple(names)))
            return self

        def keep_columns(self, *names):
            self.ops.append(KeepColumns(tuple(names)))
            return self

        def rename_column(self, old, new):
            self.ops.append(RenameColumn(old, new))
            return self

        def categorical_to_integer(self, name):
            self.ops.append(CategoricalToInteger(name))
            return self

        def categorical_to_one_hot(self, name):
            self.ops.append(CategoricalToOneHot(name))
            return self

        def string_to_categorical(self, name, categories):
            self.ops.append(StringToCategorical(name, tuple(categories)))
            return self

        def double_math_op(self, name, op, scalar):
            self.ops.append(DoubleMathOp(name, op, scalar))
            return self

        def normalize_min_max(self, name, lo, hi):
            self.ops.append(NormalizeMinMax(name, lo, hi))
            return self

        def filter_numeric(self, name, condition, value):
            self.ops.append(FilterNumericCondition(name, condition, value))
            return self

        def replace_invalid(self, name, value):
            self.ops.append(ReplaceInvalidWithValue(name, value))
            return self

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema, self.ops)

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)

    # -- execution ---------------------------------------------------------
    def _to_columns(self, records) -> Dict[str, np.ndarray]:
        names = self.initial_schema.names()
        if isinstance(records, dict):
            missing = [n for n in names if n not in records]
            if missing:
                raise ValueError(f"columnar input missing {missing}")
            return {n: np.asarray(records[n]) for n in names}
        rows = list(records)
        for r in rows:
            if len(r) != len(names):
                raise ValueError(
                    f"record width {len(r)} != schema width {len(names)}")
        return {n: np.asarray([r[i] for r in rows])
                for i, n in enumerate(names)}

    def execute(self, records) -> Dict[str, np.ndarray]:
        """Run the pipeline; returns the final columnar dict (insertion
        order = final schema order)."""
        cols = self._to_columns(records)
        for op, schema in zip(self.ops, self._schemas[:-1]):
            cols = op.apply(cols, schema)
        final = self.final_schema().names()
        return {n: cols[n] for n in final}

    def execute_to_matrix(self, records) -> np.ndarray:
        """Final columns stacked as a [rows, cols] float matrix (feeds
        DataSet/jnp directly); every final column must be numeric."""
        cols = self.execute(records)
        for name in cols:
            kind = self.final_schema().column(name).kind
            if kind not in ("double", "integer"):
                raise ValueError(
                    f"column {name!r} is {kind}; convert it before "
                    "execute_to_matrix")
        return np.stack([np.asarray(cols[n], np.float64)
                         for n in self.final_schema().names()], axis=1)

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        return {"format": "deeplearning4j_tpu/TransformProcess", "version": 1,
                "schema": self.initial_schema.to_dict(),
                "ops": [op.to_dict() for op in self.ops]}

    @staticmethod
    def from_dict(d: dict) -> "TransformProcess":
        schema = Schema.from_dict(d["schema"])
        ops = []
        for od in d["ops"]:
            od = dict(od)
            cls = _OPS[od.pop("transform")]
            kw = {k: (tuple(v) if isinstance(v, list) else v)
                  for k, v in od.items()}
            ops.append(cls(**kw))
        return TransformProcess(schema, ops)
