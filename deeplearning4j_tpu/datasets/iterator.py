"""DataSetIterator family: combinators + async prefetch.

Capability parity with the reference's datasets/iterator package
(deeplearning4j-nn/src/main/java/org/deeplearning4j/datasets/iterator/:
AsyncDataSetIterator, EarlyTerminationDataSetIterator, MultipleEpochsIterator,
DataSetIteratorSplitter, impl/BenchmarkDataSetIterator, file/FileDataSetIterator
— SURVEY.md §2.1 'Dataset iterators' row). TPU-first difference: iterators
yield host numpy batches; the jitted step's dispatch is already async, so the
prefetch thread's job is only to hide host-side ETL (parsing, augmentation),
exactly the role the reference's ADSI plays at MultiLayerNetwork.java:1265.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet


class DataSetIterator:
    """Base: iterable over DataSet batches, re-iterable via reset().

    Subclasses implement ``_produce()`` yielding DataSets. A
    ``pre_processor`` (normalizer or callable) is applied to every batch.
    """

    def __init__(self, batch_size: int = 32):
        self.batch_size = batch_size
        self.pre_processor: Optional[Callable] = None

    def set_pre_processor(self, pp):
        self.pre_processor = pp
        return self

    def _produce(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def __iter__(self):
        for ds in self._produce():
            if self.pre_processor is not None:
                ds = _apply_pp(self.pre_processor, ds)
            yield ds

    def reset(self):
        """Iterators are re-iterable by default; stateful subclasses override."""

    def __call__(self):
        """model.fit accepts callables returning a fresh iterable per epoch."""
        return iter(self)


def _apply_pp(pp, ds: DataSet) -> DataSet:
    if hasattr(pp, "transform"):
        return pp.transform(ds)
    return pp(ds)


class ListDataSetIterator(DataSetIterator):
    """Batches over an in-memory DataSet (reference ListDataSetIterator)."""

    def __init__(self, data: DataSet, batch_size: int = 32):
        super().__init__(batch_size)
        self.data = data

    def _produce(self):
        yield from self.data.batch_by(self.batch_size)

    def total_examples(self) -> int:
        return self.data.num_examples()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded buffer
    (AsyncDataSetIterator.java; queue_size = bufferSize)."""

    _SENTINEL = object()

    def __init__(self, base: Iterable, queue_size: int = 8):
        super().__init__(getattr(base, "batch_size", 32))
        self.base = base
        self.queue_size = queue_size

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        err: List[BaseException] = []

        def worker():
            try:
                src = self.base() if callable(self.base) and not hasattr(self.base, "__iter__") else self.base
                for item in src:
                    q.put(item)
            except BaseException as e:  # surface producer errors to consumer
                err.append(e)
            finally:
                q.put(self._SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is self._SENTINEL:
                break
            if self.pre_processor is not None and isinstance(item, DataSet):
                item = _apply_pp(self.pre_processor, item)
            yield item
        t.join()
        if err:
            raise err[0]


# MultiDataSet prefetch is the same machinery (reference has a separate
# AsyncMultiDataSetIterator class only because of Java generics).
AsyncMultiDataSetIterator = AsyncDataSetIterator


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of minibatches per epoch (EarlyTerminationDataSetIterator.java)."""

    def __init__(self, base: Iterable, max_batches: int):
        super().__init__(getattr(base, "batch_size", 32))
        self.base = base
        self.max_batches = max_batches

    def _produce(self):
        for i, ds in enumerate(self.base):
            if i >= self.max_batches:
                break
            yield ds


class MultipleEpochsIterator(DataSetIterator):
    """Replay the base iterator N times as one epoch (MultipleEpochsIterator.java)."""

    def __init__(self, base: Iterable, n_epochs: int):
        super().__init__(getattr(base, "batch_size", 32))
        self.base = base
        self.n_epochs = n_epochs

    def _produce(self):
        for _ in range(self.n_epochs):
            if hasattr(self.base, "reset"):
                self.base.reset()
            yield from self.base


class DataSetIteratorSplitter:
    """Split one iterator into train/test partitions by a ratio of batches
    (DataSetIteratorSplitter.java)."""

    def __init__(self, base: Iterable, total_batches: int, ratio: float):
        self.base = base
        self.n_train = int(total_batches * ratio)
        self.total = total_batches

    @property
    def train(self) -> DataSetIterator:
        outer = self

        class _Train(DataSetIterator):
            def _produce(self):
                for i, ds in enumerate(outer.base):
                    if i >= outer.n_train:
                        break
                    yield ds

        return _Train(getattr(self.base, "batch_size", 32))

    @property
    def test(self) -> DataSetIterator:
        outer = self

        class _Test(DataSetIterator):
            def _produce(self):
                for i, ds in enumerate(outer.base):
                    if i < outer.n_train:
                        continue
                    if i >= outer.total:
                        break
                    yield ds

        return _Test(getattr(self.base, "batch_size", 32))


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed-shape batches for perf tests
    (impl/BenchmarkDataSetIterator.java): one batch generated once, yielded
    N times — measures the training loop, not the ETL."""

    def __init__(self, feature_shape: Sequence[int], n_classes: int,
                 n_batches: int, seed: int = 12345):
        super().__init__(feature_shape[0])
        rs = np.random.RandomState(seed)
        x = rs.rand(*feature_shape).astype(np.float32)
        y = np.eye(n_classes, dtype=np.float32)[rs.randint(0, n_classes, feature_shape[0])]
        self.ds = DataSet(x, y)
        self.n_batches = n_batches

    def _produce(self):
        for _ in range(self.n_batches):
            yield self.ds


class FileDataSetIterator(DataSetIterator):
    """Stream DataSets saved with DataSet.save() from a directory
    (file/FileDataSetIterator.java)."""

    def __init__(self, path: str, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 12345):
        super().__init__(batch_size)
        self.path = path
        self.shuffle = shuffle
        self.seed = seed

    def _produce(self):
        files = sorted(f for f in os.listdir(self.path) if f.endswith(".npz"))
        if self.shuffle:
            np.random.RandomState(self.seed).shuffle(files)
        for f in files:
            yield DataSet.load(os.path.join(self.path, f))


class JointParallelDataSetIterator(DataSetIterator):
    """Round-robin over several iterators (parallel/JointParallelDataSetIterator.java,
    used to feed multiple DP workers distinct streams)."""

    def __init__(self, *iterators: Iterable):
        super().__init__(getattr(iterators[0], "batch_size", 32))
        self.iterators = iterators

    def _produce(self):
        actives = [iter(it) for it in self.iterators]
        while actives:
            nxt = []
            for it in actives:
                try:
                    yield next(it)
                    nxt.append(it)
                except StopIteration:
                    pass
            actives = nxt


class ShardedDataSetIterator(DataSetIterator):
    """Per-process shard of a base iterator for MULTI-HOST input pipelines
    (the dl4j-spark per-worker data plumbing, SPMD-style). Every host runs
    the SAME global stream; batches are consumed in GROUPS of N consecutive
    batches and process p takes the p-th member of each group — so every
    yielded step exists on every host (no collective deadlock from unequal
    shard counts). Groups that are incomplete (stream tail) or whose member
    batches differ in size (a short remainder batch) are dropped on ALL
    hosts identically, preserving ParallelWrapper's equal-local-batch
    invariant.

    ``process_index``/``process_count`` default to the live jax.distributed
    values; pass BOTH explicitly for testing or custom topologies."""

    def __init__(self, base, process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        super().__init__(getattr(base, "batch_size", 32))
        if (process_index is None) != (process_count is None):
            raise ValueError(
                "pass both process_index and process_count, or neither")
        self.base = base
        self._idx = process_index
        self._cnt = process_count

    def _coords(self):
        if self._idx is not None:
            return self._idx, self._cnt
        import jax

        return jax.process_index(), jax.process_count()

    def _produce(self):
        p, n = self._coords()
        if not (0 <= p < n):
            raise ValueError(f"process_index {p} out of range for {n} processes")
        src = self.base() if callable(self.base) else self.base
        group: list = []
        for ds in src:
            group.append(ds)
            if len(group) == n:
                sizes = {len(b.features) if hasattr(b, "features") else len(b[0])
                         for b in group}
                if len(sizes) == 1:   # equal-size group: safe on every host
                    yield group[p]
                group = []
        # trailing incomplete group dropped (identically on all hosts)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


def _device_put_item(item, device=None):
    """Move every array leaf of a batch onto ``device`` (default device when
    None). DataSet/MultiDataSet items are rebuilt around transferred member
    arrays; non-array leaves pass through untouched; None members survive
    (tree_map treats None as structure)."""
    import jax

    def put(a):
        if a is None or not (isinstance(a, (np.ndarray, jax.Array))
                             or hasattr(a, "__array__")):
            return a
        return jax.device_put(a, device)

    if isinstance(item, (DataSet, MultiDataSet)):
        # bypass __init__: its np.asarray() normalization would pull the
        # freshly transferred arrays straight back to host
        new = item.__class__.__new__(item.__class__)
        new.__dict__.update(
            {k: jax.tree_util.tree_map(put, v) for k, v in item.__dict__.items()})
        return new
    return jax.tree_util.tree_map(put, item)


def prefetch_to_device(iterable, depth: int = 2, device=None):
    """Generator: yield ``iterable``'s batches with array leaves already on
    device, transferred by a background thread ``depth`` batches ahead.

    ``jax.device_put`` is async, so with depth=2 this is classic double
    buffering: batch N+1's host→device copy overlaps batch N's compute
    instead of serializing with it (the AsyncDataSetIterator above only
    hides host ETL — the transfer itself still sat on the critical path).
    The producer thread blocks on a bounded queue, so at most ``depth``
    batches are resident beyond the one in use; closing the generator early
    (break / .close()) stops and joins the producer."""
    import jax  # deferred: importing this module must not init a backend

    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    err: List[BaseException] = []

    def worker():
        try:
            for item in iterable:
                item = _device_put_item(item, device)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # surface producer errors to the consumer
            err.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
    finally:
        stop.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5)
    if err:
        raise err[0]


class DevicePrefetchIterator(DataSetIterator):
    """Device-side double buffering over any batch iterable (the fit() loops
    use the ``prefetch_to_device`` generator directly; this class is the
    composable DataSetIterator face of the same machinery)."""

    def __init__(self, base: Iterable, depth: int = 2, device=None):
        super().__init__(getattr(base, "batch_size", 32))
        self.base = base
        self.depth = depth
        self.device = device

    def __iter__(self):
        src = (self.base() if callable(self.base)
               and not hasattr(self.base, "__iter__") else self.base)
        for item in prefetch_to_device(src, depth=self.depth, device=self.device):
            if self.pre_processor is not None and isinstance(item, DataSet):
                item = _apply_pp(self.pre_processor, item)
            yield item

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()
