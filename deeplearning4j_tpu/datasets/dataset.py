"""DataSet / MultiDataSet containers.

Capability parity with ND4J's DataSet/MultiDataSet (consumed throughout the
reference, e.g. nn/multilayer/MultiLayerNetwork.java fit paths; the classes
themselves live in the external nd4j-api — SURVEY.md §2.4). Host-side they
are plain numpy; the jitted step receives the arrays and XLA owns device
placement, so there is no INDArray/workspace machinery to port.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


class DataSet:
    """(features, labels, features_mask, labels_mask) bundle."""

    def __init__(self, features, labels=None, features_mask=None, labels_mask=None):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels) if labels is not None else None
        self.features_mask = np.asarray(features_mask) if features_mask is not None else None
        self.labels_mask = np.asarray(labels_mask) if labels_mask is not None else None

    # -- protocol used by model.fit (nn/model.py::_as_batch) ---------------
    def as_tuple(self):
        return (self.features, self.labels, self.features_mask, self.labels_mask)

    def __iter__(self):
        return iter(self.as_tuple())

    def __len__(self):
        return len(self.features)

    def __getitem__(self, i):
        return self.as_tuple()[i]

    def num_examples(self) -> int:
        return len(self.features)

    # -- manipulation ------------------------------------------------------
    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        idx = np.random.RandomState(seed).permutation(len(self.features))
        pick = lambda a: a[idx] if a is not None else None
        return DataSet(self.features[idx], pick(self.labels),
                       pick(self.features_mask), pick(self.labels_mask))

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        take = lambda a, s: a[s] if a is not None else None
        tr = DataSet(self.features[:n_train], take(self.labels, slice(None, n_train)),
                     take(self.features_mask, slice(None, n_train)),
                     take(self.labels_mask, slice(None, n_train)))
        te = DataSet(self.features[n_train:], take(self.labels, slice(n_train, None)),
                     take(self.features_mask, slice(n_train, None)),
                     take(self.labels_mask, slice(n_train, None)))
        return tr, te

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, len(self.features), batch_size):
            s = slice(i, i + batch_size)
            take = lambda a: a[s] if a is not None else None
            out.append(DataSet(self.features[s], take(self.labels),
                               take(self.features_mask), take(self.labels_mask)))
        return out

    def sample(self, n: int, seed: Optional[int] = None) -> "DataSet":
        idx = np.random.RandomState(seed).choice(len(self.features), n, replace=False)
        take = lambda a: a[idx] if a is not None else None
        return DataSet(self.features[idx], take(self.labels),
                       take(self.features_mask), take(self.labels_mask))

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        cat = lambda parts: (np.concatenate(parts) if parts[0] is not None else None)
        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )

    # -- persistence (ModelSerializer-style single-file container) ---------
    def save(self, path: str):
        arrs = {"features": self.features}
        if self.labels is not None:
            arrs["labels"] = self.labels
        if self.features_mask is not None:
            arrs["features_mask"] = self.features_mask
        if self.labels_mask is not None:
            arrs["labels_mask"] = self.labels_mask
        np.savez_compressed(path, **arrs)

    @staticmethod
    def load(path: str) -> "DataSet":
        with np.load(path) as z:
            return DataSet(z["features"], z.get("labels"),
                           z.get("features_mask"), z.get("labels_mask"))


class MultiDataSet:
    """Multi-input/multi-output bundle (ComputationGraph fit surface)."""

    def __init__(self, features: Sequence, labels: Sequence = (),
                 features_masks: Optional[Sequence] = None,
                 labels_masks: Optional[Sequence] = None):
        norm = lambda t: tuple(np.asarray(a) if a is not None else None for a in t) if t else None
        self.features = norm(tuple(features))
        self.labels = norm(tuple(labels))
        self.features_masks = norm(tuple(features_masks)) if features_masks else None
        self.labels_masks = norm(tuple(labels_masks)) if labels_masks else None

    def as_tuple(self):
        return (self.features, self.labels, self.features_masks, self.labels_masks)

    def __iter__(self):
        return iter(self.as_tuple())

    def __getitem__(self, i):
        return self.as_tuple()[i]

    def num_examples(self) -> int:
        return len(self.features[0])

    @staticmethod
    def merge(sets: Sequence["MultiDataSet"]) -> "MultiDataSet":
        def cat_tuple(tuples):
            if tuples[0] is None:
                return None
            n = len(tuples[0])
            return tuple(
                np.concatenate([t[i] for t in tuples]) if tuples[0][i] is not None else None
                for i in range(n)
            )

        return MultiDataSet(
            cat_tuple([s.features for s in sets]) or (),
            cat_tuple([s.labels for s in sets]) or (),
            cat_tuple([s.features_masks for s in sets]),
            cat_tuple([s.labels_masks for s in sets]),
        )
