"""Datasets: containers, iterator combinators, built-in sets, normalizers,
record readers.

TPU-native replacement for the reference's data stack — the DataSet/
MultiDataSet containers (ND4J), the datasets/iterator combinators
(deeplearning4j-nn), the built-in fetchers (deeplearning4j-core §2.2) and
the DataVec record readers (§2.4). Host-side numpy feeding the jitted step;
async prefetch hides ETL exactly like the reference's AsyncDataSetIterator.
"""

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    AsyncMultiDataSetIterator,
    BenchmarkDataSetIterator,
    DataSetIterator,
    DataSetIteratorSplitter,
    EarlyTerminationDataSetIterator,
    FileDataSetIterator,
    JointParallelDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    ShardedDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (
    CifarDataSetIterator,
    EmnistDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
    MnistDataSetIterator,
    SvhnDataSetIterator,
    TinyImageNetDataSetIterator,
    UciSequenceDataSetIterator,
    cache_dir,
    uci_synthetic_control,
)
from deeplearning4j_tpu.datasets.normalizers import (
    ImagePreProcessingScaler,
    Normalizer,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    VGG16ImagePreProcessor,
)
from deeplearning4j_tpu.datasets.transform import Schema, TransformProcess
from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "DataSet", "MultiDataSet",
    "DataSetIterator", "ListDataSetIterator", "AsyncDataSetIterator",
    "AsyncMultiDataSetIterator", "EarlyTerminationDataSetIterator",
    "MultipleEpochsIterator", "DataSetIteratorSplitter",
    "BenchmarkDataSetIterator", "FileDataSetIterator",
    "JointParallelDataSetIterator", "ShardedDataSetIterator",
    "MnistDataSetIterator", "EmnistDataSetIterator", "IrisDataSetIterator",
    "CifarDataSetIterator", "TinyImageNetDataSetIterator",
    "SvhnDataSetIterator", "LFWDataSetIterator",
    "UciSequenceDataSetIterator", "uci_synthetic_control", "cache_dir",
    "Normalizer", "NormalizerStandardize", "NormalizerMinMaxScaler",
    "VGG16ImagePreProcessor",
    "ImagePreProcessingScaler",
    "Schema", "TransformProcess",
    "CSVRecordReader", "CSVSequenceRecordReader", "ImageRecordReader",
    "RecordReaderDataSetIterator", "SequenceRecordReaderDataSetIterator",
]
