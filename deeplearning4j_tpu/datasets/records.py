"""Record readers: CSV / sequence-CSV / images → DataSets.

Capability parity with DataVec (external dependency of the reference —
SURVEY.md §2.4 'DataVec' row: record readers feeding
RecordReaderDataSetIterator). TPU-first shape: readers parse on the host
into numpy; `RecordReaderDataSetIterator` assembles fixed-shape batches that
the jitted step consumes without retraces.
"""

from __future__ import annotations

import csv
import os
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


class CSVRecordReader:
    """One row = one record of floats (DataVec CSVRecordReader).

    Plain numeric CSVs parse through the native single-pass C++ loader
    (deeplearning4j_tpu/native) when a toolchain is available; quoted or
    otherwise non-trivial files fall back to the Python csv module."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def read(self, path: str) -> np.ndarray:
        from deeplearning4j_tpu import native

        if native.available():
            with open(path, "rb") as f:
                data = f.read()
            try:
                m = native.parse_csv(data, skip_lines=self.skip_lines,
                                     delimiter=self.delimiter)
                if m is not None:
                    return m.astype(np.float32)
            except ValueError:
                pass  # quotes/exotic formatting: python csv handles it
        with open(path, newline="") as f:
            rows = list(csv.reader(f, delimiter=self.delimiter))[self.skip_lines:]
        return np.asarray([[float(v) for v in r] for r in rows if r], np.float32)


class CSVSequenceRecordReader:
    """One FILE = one sequence (DataVec CSVSequenceRecordReader as used by
    dl4j-spark's csvsequence test fixtures)."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.inner = CSVRecordReader(skip_lines, delimiter)

    def read_sequences(self, paths: Sequence[str]) -> List[np.ndarray]:
        return [self.inner.read(p) for p in paths]


class ImageRecordReader:
    """Folder-per-label image reader (DataVec ImageRecordReader): label =
    parent directory name; resizes to (height, width)."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height, self.width, self.channels = height, width, channels
        self.labels: List[str] = []

    def read_dir(self, root: str):
        from PIL import Image

        self.labels = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        xs, ys = [], []
        for li, label in enumerate(self.labels):
            d = os.path.join(root, label)
            for fn in sorted(os.listdir(d)):
                if not fn.lower().endswith((".png", ".jpg", ".jpeg", ".bmp")):
                    continue
                img = Image.open(os.path.join(d, fn))
                img = img.convert("RGB" if self.channels == 3 else "L")
                img = img.resize((self.width, self.height))
                a = np.asarray(img, np.float32) / 255.0
                if self.channels == 1:
                    a = a[..., None]
                xs.append(a)
                ys.append(li)
        x = np.stack(xs)
        y = np.eye(len(self.labels), dtype=np.float32)[np.asarray(ys)]
        return x, y


class RecordReaderDataSetIterator(DataSetIterator):
    """CSV rows → (features, one-hot label) batches (DataVec
    RecordReaderDataSetIterator: label_index column, num_classes)."""

    def __init__(self, path: str, batch_size: int, label_index: int,
                 num_classes: int, reader: Optional[CSVRecordReader] = None,
                 regression: bool = False):
        super().__init__(batch_size)
        self.rows = (reader or CSVRecordReader()).read(path)
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def _produce(self) -> Iterator[DataSet]:
        x = np.delete(self.rows, self.label_index, axis=1)
        raw = self.rows[:, self.label_index]
        if self.regression:
            y = raw[:, None].astype(np.float32)
        else:
            y = np.eye(self.num_classes, dtype=np.float32)[raw.astype(np.int64)]
        for i in range(0, len(x), self.batch_size):
            s = slice(i, i + self.batch_size)
            yield DataSet(x[s], y[s])


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Per-file sequences + per-file or per-step labels, padded + masked to
    the longest sequence in each batch (DataVec SequenceRecordReaderDataSetIterator
    with ALIGN_END-style masking)."""

    def __init__(self, feature_paths: Sequence[str], label_paths: Sequence[str],
                 batch_size: int, num_classes: int,
                 reader: Optional[CSVSequenceRecordReader] = None):
        super().__init__(batch_size)
        rdr = reader or CSVSequenceRecordReader()
        self.features = rdr.read_sequences(list(feature_paths))
        self.labels = rdr.read_sequences(list(label_paths))
        self.num_classes = num_classes

    def _produce(self) -> Iterator[DataSet]:
        for i in range(0, len(self.features), self.batch_size):
            feats = self.features[i:i + self.batch_size]
            labs = self.labels[i:i + self.batch_size]
            T = max(len(f) for f in feats)
            B, F = len(feats), feats[0].shape[1]
            x = np.zeros((B, T, F), np.float32)
            m = np.zeros((B, T), np.float32)
            y = np.zeros((B, T, self.num_classes), np.float32)
            for b, (f, l) in enumerate(zip(feats, labs)):
                x[b, : len(f)] = f
                m[b, : len(f)] = 1.0
                steps = np.asarray(l).astype(np.int64).reshape(len(l), -1)[:, -1]
                y[b, np.arange(len(l)), steps] = 1.0
            yield DataSet(x, y, m, m.copy())
