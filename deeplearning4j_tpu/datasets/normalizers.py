"""Data normalizers: fit/transform/revert, serializable.

Capability parity with ND4J's DataNormalization family
(NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler,
MultiNormalizer — external nd4j-api, embedded in model zips by
util/ModelSerializer.java:65; SURVEY.md §5.4)."""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class Normalizer:
    TYPE = "base"

    def fit(self, data) -> "Normalizer":
        """``data``: a DataSet or an iterable of DataSets."""
        sets = [data] if isinstance(data, DataSet) else list(data)
        self._fit_features(np.concatenate([np.asarray(d.features, np.float64) for d in sets]))
        return self

    def _fit_features(self, x):
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        return DataSet(self.transform_features(ds.features), ds.labels,
                       ds.features_mask, ds.labels_mask)

    def transform_features(self, x):
        raise NotImplementedError

    def revert_features(self, x):
        raise NotImplementedError

    def __call__(self, ds: DataSet) -> DataSet:
        return self.transform(ds)

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Normalizer":
        cls = {c.TYPE: c for c in (NormalizerStandardize, NormalizerMinMaxScaler,
                                   ImagePreProcessingScaler,
                                   VGG16ImagePreProcessor)}[d["@type"]]
        return cls._from_dict(d)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "Normalizer":
        return Normalizer.from_dict(json.loads(s))


class NormalizerStandardize(Normalizer):
    """Per-feature z-score over the feature axis (last axis for 2D, channel
    stats for 4D NHWC)."""

    TYPE = "standardize"

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def _axes(self, x):
        return tuple(range(x.ndim - 1))  # all but the trailing feature/channel axis

    def _fit_features(self, x):
        ax = self._axes(x)
        self.mean = x.mean(axis=ax)
        self.std = x.std(axis=ax)
        self.std[self.std < 1e-12] = 1.0

    def transform_features(self, x):
        return ((np.asarray(x) - self.mean) / self.std).astype(np.float32)

    def revert_features(self, x):
        return np.asarray(x) * self.std + self.mean

    def to_dict(self):
        return {"@type": self.TYPE, "mean": self.mean.tolist(), "std": self.std.tolist()}

    @classmethod
    def _from_dict(cls, d):
        n = cls()
        n.mean = np.asarray(d["mean"])
        n.std = np.asarray(d["std"])
        return n


class NormalizerMinMaxScaler(Normalizer):
    """Scale features into [min_range, max_range] (default [0,1])."""

    TYPE = "minmax"

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def _fit_features(self, x):
        ax = tuple(range(x.ndim - 1))
        self.data_min = x.min(axis=ax)
        self.data_max = x.max(axis=ax)

    def transform_features(self, x):
        rng = self.data_max - self.data_min
        rng = np.where(rng < 1e-12, 1.0, rng)
        unit = (np.asarray(x) - self.data_min) / rng
        return (unit * (self.max_range - self.min_range) + self.min_range).astype(np.float32)

    def revert_features(self, x):
        rng = self.data_max - self.data_min
        unit = (np.asarray(x) - self.min_range) / (self.max_range - self.min_range)
        return unit * rng + self.data_min

    def to_dict(self):
        return {"@type": self.TYPE, "min_range": self.min_range, "max_range": self.max_range,
                "data_min": self.data_min.tolist(), "data_max": self.data_max.tolist()}

    @classmethod
    def _from_dict(cls, d):
        n = cls(d["min_range"], d["max_range"])
        n.data_min = np.asarray(d["data_min"])
        n.data_max = np.asarray(d["data_max"])
        return n


class ImagePreProcessingScaler(Normalizer):
    """Fixed-range pixel scaler (0..255 → [a,b]); no fitting required."""

    TYPE = "image"

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0, max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data):
        return self

    def transform_features(self, x):
        unit = np.asarray(x, np.float32) / self.max_pixel
        return unit * (self.max_range - self.min_range) + self.min_range

    def revert_features(self, x):
        unit = (np.asarray(x) - self.min_range) / (self.max_range - self.min_range)
        return unit * self.max_pixel

    def to_dict(self):
        return {"@type": self.TYPE, "min_range": self.min_range,
                "max_range": self.max_range, "max_pixel": self.max_pixel}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["min_range"], d["max_range"], d["max_pixel"])


class VGG16ImagePreProcessor(Normalizer):
    """ImageNet per-channel mean subtraction (nd4j
    VGG16ImagePreProcessor, used by the reference's
    trainedmodels/TrainedModels.java:86 getPreProcessor): x - mean_rgb,
    no scaling. Channels-LAST here ([..., h, w, 3] NHWC) — the framework's
    native image layout."""

    TYPE = "vgg16"
    MEAN_RGB = (123.68, 116.779, 103.939)

    def fit(self, data):
        return self  # fixed statistics, nothing to fit

    @staticmethod
    def _check_nhwc(x):
        x = np.asarray(x, np.float32)
        if x.shape[-1] != 3:
            raise ValueError(
                f"VGG16ImagePreProcessor expects NHWC RGB input, got "
                f"trailing dim {x.shape[-1]}")
        return x

    def transform_features(self, x):
        return self._check_nhwc(x) - np.asarray(self.MEAN_RGB, np.float32)

    def revert_features(self, x):
        return self._check_nhwc(x) + np.asarray(self.MEAN_RGB, np.float32)

    def to_dict(self):
        return {"@type": self.TYPE}

    @classmethod
    def _from_dict(cls, d):
        return cls()
