"""Built-in dataset fetchers + iterators.

Capability parity with deeplearning4j-core's datasets/fetchers + iterator/impl
(MnistDataFetcher, EmnistDataFetcher, UciSequenceDataFetcher;
MnistDataSetIterator, CifarDataSetIterator, EmnistDataSetIterator,
IrisDataSetIterator, TinyImageNetDataSetIterator, UciSequenceDataSetIterator
— SURVEY.md §2.2). Fetchers look for the standard archives in a local cache
(``$DL4J_TPU_DATA`` or ``~/.deeplearning4j_tpu``); in air-gapped
environments (no egress) they fall back to a DETERMINISTIC synthetic
surrogate with the same shapes/classes, clearly flagged via ``.synthetic``.
UCI "synthetic control" is generated exactly — the original dataset IS a
generator's output, reproduced here from its published equations.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator, ListDataSetIterator


def cache_dir() -> str:
    d = os.environ.get("DL4J_TPU_DATA", os.path.expanduser("~/.deeplearning4j_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def _read_idx_images(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        data = f.read()
    from deeplearning4j_tpu import native

    if native.available():
        try:
            imgs = native.parse_idx_images(data)
            if imgs is not None:
                return imgs
        except ValueError:
            pass  # fall through to the Python path's clearer assert
    magic, n, h, w = struct.unpack(">IIII", data[:16])
    assert magic == 2051, f"bad idx image magic {magic}"
    return np.frombuffer(data[16:], np.uint8).reshape(n, h, w)


def _read_idx_labels(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


def _find(*names: str) -> Optional[str]:
    for root in (cache_dir(), os.path.join(cache_dir(), "mnist"), os.path.join(cache_dir(), "emnist")):
        for n in names:
            p = os.path.join(root, n)
            if os.path.exists(p):
                return p
    return None


def _synthetic_images(n: int, n_classes: int, h: int, w: int, channels: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-conditional image surrogate: a fixed per-class
    template plus pixel noise — separable (so training curves move) and
    reproducible across runs. Templates are seeded by (dataset shape, class
    count) ONLY, so train and test splits share the same class structure."""
    template_rs = np.random.RandomState(1_000_003 + n_classes * 17 + h * 7 + channels)
    shape = (h, w) if channels == 1 else (h, w, channels)
    templates = template_rs.rand(n_classes, *shape).astype(np.float32)
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, n_classes, n)
    noise = rs.rand(n, *shape).astype(np.float32)
    imgs = np.clip(0.7 * templates[labels] + 0.3 * noise, 0, 1) * 255.0
    return imgs.astype(np.uint8), labels.astype(np.int64)


class MnistDataFetcher:
    """MNIST loader: idx archives from the cache dir, else synthetic
    surrogate (datasets/fetchers/MnistDataFetcher.java)."""

    N_CLASSES = 10
    H = W = 28

    def __init__(self, train: bool = True, seed: int = 12345):
        img = _find(*(["train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"] if train
                      else ["t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte.gz"]))
        lbl = _find(*(["train-labels-idx1-ubyte", "train-labels-idx1-ubyte.gz"] if train
                      else ["t10k-labels-idx1-ubyte", "t10k-labels-idx1-ubyte.gz"]))
        if img and lbl:
            self.images = _read_idx_images(img)
            self.labels = _read_idx_labels(lbl)
            self.synthetic = False
        else:
            n = 60000 if train else 10000
            n = int(os.environ.get("DL4J_TPU_SYNTH_N", n))
            self.images, self.labels = _synthetic_images(
                n, self.N_CLASSES, self.H, self.W, 1, seed + (0 if train else 1)
            )
            self.synthetic = True

    def dataset(self, binarize: bool = False, flatten: bool = False) -> DataSet:
        x = self.images.astype(np.float32) / 255.0
        if binarize:
            x = (x > 0.5).astype(np.float32)
        x = x.reshape(len(x), -1) if flatten else x[..., None]  # NHWC
        y = np.eye(self.N_CLASSES, dtype=np.float32)[self.labels]
        return DataSet(x, y)


class EmnistDataFetcher(MnistDataFetcher):
    """EMNIST splits (datasets/fetchers/EmnistDataFetcher.java). Class count
    per split; idx files share MNIST's format."""

    SPLITS = {"balanced": 47, "byclass": 62, "bymerge": 47, "digits": 10,
              "letters": 26, "mnist": 10}

    def __init__(self, split: str = "balanced", train: bool = True, seed: int = 12345):
        self.N_CLASSES = self.SPLITS[split]
        prefix = f"emnist-{split}-{'train' if train else 'test'}"
        img = _find(f"{prefix}-images-idx3-ubyte", f"{prefix}-images-idx3-ubyte.gz")
        lbl = _find(f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels-idx1-ubyte.gz")
        if img and lbl:
            self.images = _read_idx_images(img)
            self.labels = _read_idx_labels(lbl)
            if split == "letters":  # letters labels are 1-based
                self.labels = self.labels - 1
            self.synthetic = False
        else:
            n = int(os.environ.get("DL4J_TPU_SYNTH_N", 10000))
            # stable per-split offset (hash() is randomized per process by
            # PYTHONHASHSEED and would break the deterministic surrogate)
            split_seed = sum(ord(c) for c in split) % 1000
            self.images, self.labels = _synthetic_images(
                n, self.N_CLASSES, 28, 28, 1, seed + split_seed
            )
            self.synthetic = True


class MnistDataSetIterator(ListDataSetIterator):
    """datasets/iterator/impl/MnistDataSetIterator.java."""

    def __init__(self, batch_size: int, train: bool = True, binarize: bool = False,
                 shuffle: bool = True, seed: int = 12345, flatten: bool = False,
                 num_examples: Optional[int] = None):
        f = MnistDataFetcher(train, seed)
        ds = f.dataset(binarize, flatten)
        if shuffle:
            ds = ds.shuffle(seed)
        if num_examples is not None:
            ds, _ = ds.split_test_and_train(num_examples)
        super().__init__(ds, batch_size)
        self.synthetic = f.synthetic


class EmnistDataSetIterator(ListDataSetIterator):
    def __init__(self, split: str, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 12345):
        f = EmnistDataFetcher(split, train, seed)
        ds = f.dataset()
        if shuffle:
            ds = ds.shuffle(seed)
        super().__init__(ds, batch_size)
        self.synthetic = f.synthetic


class IrisDataSetIterator(ListDataSetIterator):
    """The real Fisher iris data (datasets/iterator/impl/IrisDataSetIterator.java)."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150, seed: int = 12345):
        from sklearn.datasets import load_iris  # offline, bundled data

        d = load_iris()
        x = d.data.astype(np.float32)
        y = np.eye(3, dtype=np.float32)[d.target]
        idx = np.random.RandomState(seed).permutation(len(x))[:num_examples]
        super().__init__(DataSet(x[idx], y[idx]), batch_size)


class CifarDataSetIterator(ListDataSetIterator):
    """CIFAR-10 (datasets/iterator/impl/CifarDataSetIterator.java): python
    pickle batches from the cache dir, else synthetic surrogate."""

    N_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True, shuffle: bool = True,
                 seed: int = 12345, num_examples: Optional[int] = None):
        root = os.path.join(cache_dir(), "cifar-10-batches-py")
        xs, ys = [], []
        if os.path.isdir(root):
            import pickle

            names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
            for n in names:
                with open(os.path.join(root, n), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32))
                ys.append(np.asarray(d[b"labels"], np.int64))
            x = np.concatenate(xs).transpose(0, 2, 3, 1)  # NHWC
            y = np.concatenate(ys)
            self.synthetic = False
        else:
            n = int(os.environ.get("DL4J_TPU_SYNTH_N", 50000 if train else 10000))
            x, y = _synthetic_images(n, 10, 32, 32, 3, seed + (2 if train else 3))
            self.synthetic = True
        xf = x.astype(np.float32) / 255.0
        yf = np.eye(self.N_CLASSES, dtype=np.float32)[y]
        ds = DataSet(xf, yf)
        if shuffle:
            ds = ds.shuffle(seed)
        if num_examples is not None:
            ds, _ = ds.split_test_and_train(num_examples)
        super().__init__(ds, batch_size)


class TinyImageNetDataSetIterator(ListDataSetIterator):
    """TinyImageNet 64x64x3, 200 classes (TinyImageNetFetcher.java); images
    load from the cache-dir folder layout
    (``tiny-imagenet-200/train/<wnid>/images/*.JPEG``) when present and PIL
    is importable, else a deterministic synthetic surrogate."""

    N_CLASSES = 200

    def __init__(self, batch_size: int, train: bool = True, seed: int = 12345,
                 num_examples: Optional[int] = None):
        loaded = self._try_load_folder(train, num_examples)
        if loaded is not None:
            x, y = loaded
            self.synthetic = False
        else:
            n = int(os.environ.get("DL4J_TPU_SYNTH_N", 2000))
            x, y = _synthetic_images(n, self.N_CLASSES, 64, 64, 3, seed + 7)
            self.synthetic = True
        ds = DataSet(x.astype(np.float32) / 255.0,
                     np.eye(self.N_CLASSES, dtype=np.float32)[y])
        if num_examples is not None:
            ds, _ = ds.split_test_and_train(num_examples)
        super().__init__(ds, batch_size)

    def _try_load_folder(self, train: bool, limit: Optional[int]):
        root = os.path.join(cache_dir(), "tiny-imagenet-200")
        split_dir = os.path.join(root, "train" if train else "val")
        if not os.path.isdir(split_dir):
            return None
        try:
            from PIL import Image
        except ImportError:
            return None
        wnids_file = os.path.join(root, "wnids.txt")
        if not os.path.exists(wnids_file):
            return None
        wnids = [w.strip() for w in open(wnids_file) if w.strip()]
        cls_of = {w: i for i, w in enumerate(wnids)}
        xs, ys = [], []
        for wnid in wnids:
            img_dir = os.path.join(split_dir, wnid, "images")
            if not os.path.isdir(img_dir):
                continue
            for fn in sorted(os.listdir(img_dir)):
                img = Image.open(os.path.join(img_dir, fn)).convert("RGB")
                xs.append(np.asarray(img, np.uint8))
                ys.append(cls_of[wnid])
                if limit is not None and len(xs) >= limit:
                    break
            if limit is not None and len(xs) >= limit:
                break
        if not xs:
            return None
        return np.stack(xs), np.asarray(ys, np.int64)


def uci_synthetic_control(n_per_class: int = 100, timesteps: int = 60,
                          seed: int = 12345) -> Tuple[np.ndarray, np.ndarray]:
    """The UCI 'synthetic control chart' generator (6 classes): normal,
    cyclic, increasing trend, decreasing trend, upward shift, downward shift.
    (UciSequenceDataFetcher.java downloads the dataset; it was itself
    generated from these equations, so we generate it directly.)"""
    rs = np.random.RandomState(seed)
    t = np.arange(timesteps, dtype=np.float64)
    series, labels = [], []
    for cls in range(6):
        for _ in range(n_per_class):
            m, s = 30.0, 2.0
            r = rs.rand(timesteps)
            base = m + s * (r - 0.5) * 2
            if cls == 1:  # cyclic
                a, T = 15.0 * rs.rand() + 10.0, 10.0 + 5.0 * rs.rand()
                base = base + a * np.sin(2 * np.pi * t / T)
            elif cls == 2:  # increasing trend
                base = base + (0.2 + 0.3 * rs.rand()) * t
            elif cls == 3:  # decreasing trend
                base = base - (0.2 + 0.3 * rs.rand()) * t
            elif cls == 4:  # upward shift
                t3 = rs.randint(timesteps // 3, 2 * timesteps // 3)
                base = base + (t >= t3) * (7.5 + 12.5 * rs.rand())
            elif cls == 5:  # downward shift
                t3 = rs.randint(timesteps // 3, 2 * timesteps // 3)
                base = base - (t >= t3) * (7.5 + 12.5 * rs.rand())
            series.append(base)
            labels.append(cls)
    x = np.asarray(series, np.float32)[..., None]  # [N, T, 1]
    y = np.eye(6, dtype=np.float32)[np.asarray(labels)]
    return x, y


class UciSequenceDataSetIterator(ListDataSetIterator):
    """Sequence classification set (UciSequenceDataSetIterator.java):
    labels broadcast per-timestep for RnnOutputLayer heads."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 12345):
        x, y = uci_synthetic_control(seed=seed)
        idx = np.random.RandomState(seed + 1).permutation(len(x))
        cut = int(0.75 * len(x))
        pick = idx[:cut] if train else idx[cut:]
        yy = np.repeat(y[pick][:, None, :], x.shape[1], axis=1)  # [N, T, C]
        super().__init__(DataSet(x[pick], yy), batch_size)


class SvhnDataSetIterator(ListDataSetIterator):
    """SVHN 32x32x3 digits (datasets/fetchers/SvhnDataFetcher.java): loads
    the cropped-digits .mat files from the cache dir when scipy is
    importable, else a deterministic synthetic surrogate."""

    N_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True, seed: int = 12345,
                 num_examples: Optional[int] = None):
        loaded = self._try_load_mat(train)
        if loaded is not None:
            x, y = loaded
            self.synthetic = False
        else:
            n = int(os.environ.get("DL4J_TPU_SYNTH_N", 5000))
            x, y = _synthetic_images(n, self.N_CLASSES, 32, 32, 3,
                                     seed + (11 if train else 12))
            self.synthetic = True
        ds = DataSet(x.astype(np.float32) / 255.0,
                     np.eye(self.N_CLASSES, dtype=np.float32)[y])
        if num_examples is not None:
            ds, _ = ds.split_test_and_train(num_examples)
        super().__init__(ds, batch_size)

    def _try_load_mat(self, train: bool):
        path = os.path.join(cache_dir(),
                            "train_32x32.mat" if train else "test_32x32.mat")
        if not os.path.exists(path):
            return None
        try:
            from scipy.io import loadmat
        except ImportError:
            return None
        d = loadmat(path)
        x = np.transpose(d["X"], (3, 0, 1, 2))          # HWCN -> NHWC
        y = d["y"].ravel().astype(np.int64) % 10        # SVHN labels digit '0' as 10
        return x, y


class LFWDataSetIterator(ListDataSetIterator):
    """Labeled Faces in the Wild (datasets/iterator/impl/LFWDataSetIterator.java):
    ``lfw/<person>/<person>_NNNN.jpg`` folders from the cache dir when PIL is
    importable, else a synthetic surrogate. ``num_labels``: keep the N most
    frequent identities (the reference's numLabels knob)."""

    def __init__(self, batch_size: int, image_shape: Tuple[int, int, int] = (64, 64, 3),
                 num_labels: int = 10, train: bool = True, seed: int = 12345,
                 num_examples: Optional[int] = None):
        h, w, c = image_shape
        self.num_labels = num_labels
        loaded = self._try_load_folder(h, w, num_labels)
        if loaded is not None:
            x, y = loaded
            self.synthetic = False
        else:
            n = int(os.environ.get("DL4J_TPU_SYNTH_N", 1000))
            x, y = _synthetic_images(n, num_labels, h, w, c,
                                     seed + (13 if train else 14))
            self.synthetic = True
        ds = DataSet(x.astype(np.float32) / 255.0,
                     np.eye(num_labels, dtype=np.float32)[y])
        if num_examples is not None:
            ds, _ = ds.split_test_and_train(num_examples)
        super().__init__(ds, batch_size)

    def _try_load_folder(self, h: int, w: int, num_labels: int):
        root = os.path.join(cache_dir(), "lfw")
        if not os.path.isdir(root):
            return None
        try:
            from PIL import Image
        except ImportError:
            return None
        def n_images(d):
            return sum(1 for f in os.listdir(os.path.join(root, d))
                       if f.lower().endswith((".jpg", ".jpeg", ".png")))

        people = sorted(
            (d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
             and n_images(d) > 0),
            key=lambda d: -n_images(d))[:num_labels]
        xs, ys = [], []
        for li, person in enumerate(people):
            pdir = os.path.join(root, person)
            for f in sorted(os.listdir(pdir)):
                if not f.lower().endswith((".jpg", ".jpeg", ".png")):
                    continue
                img = Image.open(os.path.join(pdir, f)).convert("RGB").resize((w, h))
                xs.append(np.asarray(img, np.uint8))
                ys.append(li)
        if not xs:
            return None
        return np.stack(xs), np.asarray(ys, np.int64)
