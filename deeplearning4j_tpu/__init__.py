"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/pjit/Pallas re-design with the capabilities of Eclipse
Deeplearning4j (reference: allwefantasy/deeplearning4j @ v0.9.2-SNAPSHOT):
configuration-driven layer library, sequential (MultiLayerNetwork) and DAG
(ComputationGraph) models, single-compiled-executable training steps, full
evaluation / early-stopping / checkpointing tooling, and mesh-sharded
data/tensor parallelism replacing ParallelWrapper / Spark masters / Aeron
parameter server with XLA collectives over ICI/DCN.

Where the reference dispatches per-op JNI kernels (SURVEY.md §3.1), this
framework traces the whole ``step(params, opt_state, batch)`` into one XLA
executable with HBM-resident parameters.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn import activations, initializers, losses
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.config import LayerConfig, layer_registry

__all__ = [
    "InputType",
    "LayerConfig",
    "layer_registry",
    "activations",
    "initializers",
    "losses",
]
