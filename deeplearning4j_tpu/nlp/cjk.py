"""CJK morphological tokenization: lattice Viterbi segmentation.

Capability parity target (SURVEY.md §2.7 CJK row): the reference vendors
full third-party morphological analyzers — ansj for Chinese
(deeplearning4j-nlp-chinese, ~9.5K LoC + dictionaries), kuromoji for
Japanese (deeplearning4j-nlp-japanese, ~6.8K LoC + IPADIC), and
open-korean-text glue (deeplearning4j-nlp-korean) — each a Viterbi lattice
over a lexicon with word/connection costs plus an unknown-word model.

This module implements that same ALGORITHMIC core natively:

- :class:`LatticeSegmenter` — a Viterbi shortest-path over a word lattice:
  dictionary edges from a cost-weighted lexicon (longest-match prefix scan),
  unknown-word edges from a script-class model (same-script runs group,
  singletons carry a penalty), additive costs (no connection matrix — the
  documented simplification vs ansj/kuromoji).
- Per-language factories with COMPACT embedded lexicons (high-frequency
  function words, particles and everyday vocabulary) and ``user_dict``
  extension — the kuromoji UserDictionary / ansj UserDefineLibrary surface.

Scope, stated plainly: the embedded lexicons are a few hundred entries, not
the reference's megabyte dictionaries; part-of-speech tags, readings and
named-entity recognizers are out of scope. What IS equivalent: genuine
dictionary-driven segmentation (not the char-bigram fallback in
tokenization.py), user dictionaries, per-script unknown-word handling, and
the reference factory surface (ChineseTokenizerFactory /
JapaneseTokenizerFactory / KoreanTokenizerFactory names).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# script classes
# ---------------------------------------------------------------------------


def _script(ch: str) -> str:
    cp = ord(ch)
    if (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0xF900 <= cp <= 0xFAFF
            or 0x20000 <= cp <= 0x3FFFF):   # supplementary-plane ideographs
        return "han"
    if 0x3040 <= cp <= 0x309F:
        return "hiragana"
    if 0x30A0 <= cp <= 0x30FF or 0xFF66 <= cp <= 0xFF9F:  # + half-width
        return "katakana"
    if 0xAC00 <= cp <= 0xD7A3 or 0x1100 <= cp <= 0x11FF:
        return "hangul"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


# ---------------------------------------------------------------------------
# lattice segmenter
# ---------------------------------------------------------------------------


class LatticeSegmenter:
    """Viterbi shortest path over the segmentation lattice of a string.

    ``lexicon``: {word: cost} — LOWER is preferred; typical range 1-10.
    Unknown-word edges: a run of same-script characters costs
    ``unk_base + unk_per_char * len`` (runs group); a single character
    always has a fallback edge so segmentation never fails.
    """

    def __init__(self, lexicon: Dict[str, float], *, unk_base: float = 12.0,
                 unk_per_char: float = 1.0):
        self.lexicon = dict(lexicon)
        self.unk_base = unk_base
        self.unk_per_char = unk_per_char
        self.max_len = max((len(w) for w in self.lexicon), default=1)
        # prefix set for the longest-match scan (trie-lite: Python dict
        # lookups on slices beat a pointer trie at these lexicon sizes)
        self._prefixes = {w[:i] for w in self.lexicon for i in range(1, len(w))}

    def add(self, word: str, cost: float = 2.0):
        self.lexicon[word] = cost
        self.max_len = max(self.max_len, len(word))
        for i in range(1, len(word)):
            self._prefixes.add(word[:i])

    def segment(self, text: str) -> List[str]:
        n = len(text)
        if n == 0:
            return []
        INF = float("inf")
        best = [INF] * (n + 1)
        back: List[Tuple[int, str]] = [(-1, "")] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == INF:
                continue
            # dictionary edges (longest-match scan, pruned by prefixes)
            j = i + 1
            limit = min(n, i + self.max_len)
            while j <= limit:
                w = text[i:j]
                cost = self.lexicon.get(w)
                if cost is not None and best[i] + cost < best[j]:
                    best[j] = best[i] + cost
                    back[j] = (i, w)
                if j < limit and w not in self._prefixes and w not in self.lexicon:
                    break
                j += 1
            # unknown edges. Whole-run grouping only for scripts whose
            # unknown words ARE runs (katakana loan words, latin, digits,
            # hangul eojeol); han/hiragana unknowns fall back to single
            # characters so dictionary hits next to them still win.
            sc = _script(text[i])
            if sc in ("katakana", "latin", "digit", "hangul"):
                k = i + 1
                while k < n and _script(text[k]) == sc:
                    k += 1
                c = best[i] + self.unk_base + self.unk_per_char * (k - i)
                if c < best[k]:
                    best[k] = c
                    back[k] = (i, text[i:k])
            c = best[i] + self.unk_base + self.unk_per_char + 2.0
            if c < best[i + 1]:
                best[i + 1] = c
                back[i + 1] = (i, text[i])
        # backtrace
        out: List[str] = []
        pos = n
        while pos > 0:
            i, w = back[pos]
            out.append(w)
            pos = i
        out.reverse()
        return out


# ---------------------------------------------------------------------------
# embedded lexicons (compact high-frequency sets; costs: common=1-2,
# ordinary=3-4). Extend per instance via user_dict.
# ---------------------------------------------------------------------------

_ZH_LEXICON = {w: c for c, ws in {
    1.0: ["的", "了", "是", "在", "我", "有", "和", "就", "不", "人", "都",
          "一个", "我们", "你们", "他们", "这个", "那个", "什么", "没有",
          "可以", "自己", "这", "那", "他", "她", "它", "你", "与", "也"],
    2.0: ["中国", "北京", "上海", "今天", "明天", "现在", "时候", "时间",
          "知道", "觉得", "喜欢", "学习", "工作", "朋友", "老师", "学生",
          "问题", "世界", "国家", "地方", "东西", "事情", "孩子", "因为",
          "所以", "但是", "如果", "已经", "还是", "或者", "非常", "很",
          "大", "小", "多", "少", "好", "新", "来", "去", "说", "看",
          "想", "要", "会", "能", "到", "从", "对", "给", "被", "把"],
    3.0: ["深度", "机器", "模型", "数据", "训练", "神经", "网络",
          "语言", "文字", "科学", "技术", "公司", "大学", "电脑", "手机",
          "经济", "历史", "文化", "音乐", "电影", "汉语", "英语", "高兴",
          "漂亮", "便宜", "开始", "结束", "帮助", "希望", "认为", "发现"],
}.items() for w in ws}

_JA_LEXICON = {w: c for c, ws in {
    1.0: ["の", "は", "が", "を", "に", "で", "と", "も", "へ", "や",
          "から", "まで", "より", "です", "ます", "でした", "ました",
          "ない", "する", "した", "いる", "ある", "なる", "これ", "それ",
          "あれ", "この", "その", "あの", "私", "あなた", "何", "だ"],
    2.0: ["日本", "東京", "今日", "明日", "時間", "学生", "先生", "学校",
          "会社", "仕事", "友達", "言葉", "世界", "問題", "勉強", "研究",
          "大学", "電車", "天気", "映画", "音楽", "料理", "好き", "大きい",
          "小さい", "新しい", "行く", "来る", "見る", "食べる", "飲む",
          "読む", "書く", "話す", "聞く", "思う", "言う", "知る", "とても"],
    3.0: ["機械", "学習", "深層", "モデル", "データ", "訓練", "計算",
          "言語", "科学", "技術", "自然", "処理", "人工", "知能"],
}.items() for w in ws}

# Korean postpositions (josa) and common endings — suffix-stripped from
# space-delimited words (the open-korean-text stemming surface)
_KO_JOSA = ["은", "는", "이", "가", "을", "를", "의", "에", "에서", "에게",
            "께", "와", "과", "랑", "이랑", "로", "으로", "부터", "까지",
            "만", "도", "보다", "처럼", "같이", "하고", "이나", "나", "요"]
_KO_JOSA_BY_LEN = sorted(_KO_JOSA, key=len, reverse=True)

# josa as first-class lattice entries: the segmenter itself splits
# "학교에서" -> 학교 + 에서 (the word_filter below covers unknown stems)
# one entry per surface form: words listed in a tier must NOT repeat in
# _KO_JOSA (the josa cost is authoritative for shared surfaces like 이/나/보다)
_KO_LEXICON = {j: 1.2 for j in _KO_JOSA}
_KO_LEXICON.update({w: c for c, ws in {
    1.0: ["그", "저", "것", "수", "안", "못", "더", "잘", "또",
          "하다", "있다", "없다", "되다", "이다", "아니다", "우리", "나",
          "너", "그리고", "그러나", "하지만", "그래서"],
    2.0: ["한국", "서울", "오늘", "내일", "시간", "학생", "선생님", "학교",
          "회사", "일", "친구", "말", "세계", "문제", "공부", "연구",
          "대학", "날씨", "영화", "음악", "음식", "사람", "사랑", "좋다",
          "크다", "작다", "새롭다", "가다", "오다", "먹다",
          "마시다", "읽다", "쓰다", "말하다", "듣다", "생각하다", "알다"],
    3.0: ["기계", "학습", "심층", "모델", "데이터", "훈련", "계산", "언어",
          "과학", "기술", "자연", "처리", "인공", "지능"],
}.items() for w in ws})


# ---------------------------------------------------------------------------
# tokenizers / factories (the reference factory surface)
# ---------------------------------------------------------------------------


class _LatticeTokenizer:
    """Tokenizer over a LatticeSegmenter; non-CJK runs (latin words,
    numbers) pass through whole; whitespace/punctuation separate."""

    def __init__(self, text: str, seg: LatticeSegmenter,
                 pre: Optional[Callable[[str], str]] = None,
                 word_filter: Optional[Callable[[str], List[str]]] = None):
        toks: List[str] = []
        buf: List[str] = []
        buf_kind = None  # "cjk" | "word"

        def flush():
            nonlocal buf_kind
            if not buf:
                return
            chunk = "".join(buf)
            if buf_kind == "cjk":
                toks.extend(seg.segment(chunk))
            else:
                toks.append(chunk)
            buf.clear()
            buf_kind = None

        for ch in text:
            sc = _script(ch)
            if sc in ("han", "hiragana", "katakana", "hangul"):
                if buf_kind != "cjk":
                    flush()
                buf_kind = "cjk"
                buf.append(ch)
            elif sc in ("latin", "digit"):
                if buf_kind != "word":
                    flush()
                buf_kind = "word"
                buf.append(ch)
            else:
                flush()
        flush()
        if word_filter is not None:
            toks = [t for w in toks for t in word_filter(w)]
        if pre is not None:
            toks = [t for t in (pre(t) for t in toks) if t]
        self._tokens = toks
        self._i = 0

    def has_more_tokens(self) -> bool:
        return self._i < len(self._tokens)

    def next_token(self) -> str:
        t = self._tokens[self._i]
        self._i += 1
        return t

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)


class _BaseCJKFactory:
    """Shared factory plumbing (user_dict, preprocessor, tokenize)."""

    _lexicon: Dict[str, float] = {}

    def __init__(self, user_dict: Optional[Iterable[str]] = None,
                 preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor
        self._seg = LatticeSegmenter(dict(self._lexicon))
        for w in user_dict or ():
            self._seg.add(w, 1.5)     # user entries outrank built-ins

    def add_word(self, word: str, cost: float = 1.5):
        """ansj UserDefineLibrary.insertWord / kuromoji UserDictionary."""
        self._seg.add(word, cost)
        return self

    def set_token_pre_processor(self, pre: Callable):
        self.preprocessor = pre
        return self

    def _word_filter(self, w: str) -> List[str]:
        return [w]

    def create(self, text: str) -> _LatticeTokenizer:
        return _LatticeTokenizer(text, self._seg, self.preprocessor,
                                 self._word_filter)

    def tokenize(self, text: str) -> List[str]:
        return self.create(text).get_tokens()


class ChineseTokenizerFactory(_BaseCJKFactory):
    """Dictionary-lattice Chinese segmentation
    (tokenizerfactory/ChineseTokenizerFactory.java over ansj's
    ToAnalysis — NlpAnalysis' extra NER layers are out of scope)."""

    _lexicon = _ZH_LEXICON


class JapaneseTokenizerFactory(_BaseCJKFactory):
    """Dictionary-lattice Japanese segmentation
    (tokenizerfactory/JapaneseTokenizerFactory.java over kuromoji).
    Katakana loan-word runs group via the unknown-word script model;
    ``baseForm`` conjugation lookup is out of scope."""

    _lexicon = _JA_LEXICON


class KoreanTokenizerFactory(_BaseCJKFactory):
    """Korean tokenization (tokenizerfactory/KoreanTokenizerFactory.java
    over open-korean-text): lattice over hangul runs, then josa
    (postposition) stripping — the morphological normalization that makes
    '학교에서' and '학교' share an embedding row."""

    _lexicon = _KO_LEXICON

    def _word_filter(self, w: str) -> List[str]:
        # suffix-strip the longest matching particle, keep both morphemes
        if len(w) >= 2 and _script(w[0]) == "hangul" and w not in self._seg.lexicon:
            for josa in _KO_JOSA_BY_LEN:
                if w.endswith(josa) and len(w) > len(josa):
                    return [w[:-len(josa)], josa]
        return [w]
