"""SequenceVectors / Word2Vec / ParagraphVectors — TPU-native embedding training.

Capability parity with the reference's embedding stack (SURVEY.md §2.7):
models/sequencevectors/SequenceVectors.java:49 (fit:192, trainSequence:342),
learning/impl/elements/{SkipGram,CBOW}.java, models/word2vec/Word2Vec.java,
models/paragraphvectors/ParagraphVectors.java,
models/embeddings/inmemory/InMemoryLookupTable.java.

TPU-first redesign: the reference trains with per-pair axpy ops on JVM
threads (AsyncSequencer producer + VectorCalculationsThread consumers,
SequenceVectors.java:1021,1127). Here training pairs are generated host-side
into BATCHED index arrays and each batch is ONE jitted step: gathers of the
embedding rows, a dot-product logistic loss (negative sampling) or Huffman
hierarchical softmax, and scatter-adds back — all fused by XLA, with the
embedding matmuls on the MXU. Same objective, same hyperparameters
(window, negative, subsampling, lr decay), orders of magnitude fewer
dispatches.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    VocabConstructor,
    build_huffman,
    huffman_tables,
    subsample_probs,
    unigram_table,
)

# ---------------------------------------------------------------------------
# jitted steps
# ---------------------------------------------------------------------------


def _sg_ns_step(params, centers, contexts, negs, lr):
    """Skip-gram negative sampling: one batch, full fused update.

    centers/contexts: [B] int32; negs: [B,K] int32.
    loss = -log σ(c·t) - Σ log σ(-c·n).
    """
    syn0, syn1 = params["syn0"], params["syn1neg"]
    c = syn0[centers]                       # [B,D]
    t = syn1[contexts]                      # [B,D]
    n = syn1[negs]                          # [B,K,D]

    pos_dot = jnp.sum(c * t, axis=-1)                     # [B]
    neg_dot = jnp.einsum("bd,bkd->bk", c, n)              # [B,K]
    loss = -jnp.mean(
        jax.nn.log_sigmoid(pos_dot) + jnp.sum(jax.nn.log_sigmoid(-neg_dot), axis=-1)
    )

    # manual gradients (cheaper than autodiff's full-vocab zeros):
    gpos = jax.nn.sigmoid(pos_dot) - 1.0                  # [B]
    gneg = jax.nn.sigmoid(neg_dot)                        # [B,K]
    d_c = gpos[:, None] * t + jnp.einsum("bk,bkd->bd", gneg, n)
    d_t = gpos[:, None] * c
    d_n = gneg[..., None] * c[:, None, :]

    syn0 = syn0.at[centers].add(-lr * d_c)
    syn1 = syn1.at[contexts].add(-lr * d_t)
    syn1 = syn1.at[negs.reshape(-1)].add(-lr * d_n.reshape(-1, d_n.shape[-1]))
    return {"syn0": syn0, "syn1neg": syn1, **{k: v for k, v in params.items()
                                              if k not in ("syn0", "syn1neg")}}, loss


def _sg_ns_epoch_scan(params, centers2d, contexts2d, cum_table, key,
                      lr0, min_lr, seen0, total, negative: int,
                      unroll: int = 4):
    """lax.scan of _sg_ns_step over [N, B] pair chunks, negatives drawn
    ON-DEVICE by inverse-CDF over the unigram table. One dispatch (and ONE
    host->device transfer of the pair arrays) covers N batches — through a
    remote/tunneled device this removes the per-batch RTT that otherwise
    dominates end-to-end corpus training (docs/PERF.md Word2Vec)."""
    N, B = centers2d.shape

    def body(carry, xs):
        prm, k, seen = carry
        c, t = xs
        k, sub = jax.random.split(k)
        u = jax.random.uniform(sub, (B, negative))
        negs = jnp.clip(jnp.searchsorted(cum_table, u),
                        0, cum_table.shape[0] - 1).astype(jnp.int32)
        frac = jnp.minimum(seen / total, 1.0)
        lr = jnp.maximum(lr0 * (1.0 - frac), min_lr)
        prm, loss = _sg_ns_step(prm, c, t, negs, lr)
        return (prm, k, seen + B), loss

    # unroll=4 default: scan-of-scatter on TPU runs ~4x faster partially
    # unrolled (measured 283 -> 64 ms/step at B=64K, V=100K; unroll=16 is
    # no better and triples compile time). unroll=1 ~halves the first-epoch
    # compile (52.2s at the bench config, BENCH_r04) at ~4x warm-epoch cost
    # -- or keep 4 and amortize compiles across processes with
    # utils/compile_cache.enable_compilation_cache.
    (params, _, _), losses = jax.lax.scan(
        body, (params, key, jnp.asarray(seen0, jnp.float32)),
        (centers2d, contexts2d), unroll=unroll)
    return params, losses


def _cbow_ns_step(params, context_win, win_mask, targets, negs, lr):
    """CBOW negative sampling: mean of window vectors predicts the target.

    context_win: [B,W] int32 (padded), win_mask: [B,W], targets: [B],
    negs: [B,K].
    """
    syn0, syn1 = params["syn0"], params["syn1neg"]
    ctx = syn0[context_win]                                # [B,W,D]
    cnt = jnp.maximum(jnp.sum(win_mask, axis=-1, keepdims=True), 1.0)
    h = jnp.sum(ctx * win_mask[..., None], axis=1) / cnt   # [B,D]
    t = syn1[targets]
    n = syn1[negs]
    pos_dot = jnp.sum(h * t, axis=-1)
    neg_dot = jnp.einsum("bd,bkd->bk", h, n)
    loss = -jnp.mean(
        jax.nn.log_sigmoid(pos_dot) + jnp.sum(jax.nn.log_sigmoid(-neg_dot), axis=-1)
    )
    gpos = jax.nn.sigmoid(pos_dot) - 1.0
    gneg = jax.nn.sigmoid(neg_dot)
    d_h = gpos[:, None] * t + jnp.einsum("bk,bkd->bd", gneg, n)   # [B,D]
    d_t = gpos[:, None] * h
    d_n = gneg[..., None] * h[:, None, :]
    d_ctx = (d_h / cnt)[:, None, :] * win_mask[..., None]          # [B,W,D]

    syn0 = syn0.at[context_win.reshape(-1)].add(-lr * d_ctx.reshape(-1, d_ctx.shape[-1]))
    syn1 = syn1.at[targets].add(-lr * d_t)
    syn1 = syn1.at[negs.reshape(-1)].add(-lr * d_n.reshape(-1, d_n.shape[-1]))
    return {"syn0": syn0, "syn1neg": syn1, **{k: v for k, v in params.items()
                                              if k not in ("syn0", "syn1neg")}}, loss


def _dm_ns_step(params, doc_ids, context_win, win_mask, targets, negs, lr):
    """PV-DM negative sampling (models/embeddings/learning/impl/sequence/
    DM.java): the document vector and the window-word average JOINTLY (mean
    over doc + context vectors) predict the center word.

    doc_ids: [B] int32 (label rows of syn0); context_win: [B,W] padded,
    win_mask: [B,W]; targets: [B]; negs: [B,K].
    """
    syn0, syn1 = params["syn0"], params["syn1neg"]
    ctx = syn0[context_win]                                # [B,W,D]
    doc = syn0[doc_ids]                                    # [B,D]
    cnt = jnp.sum(win_mask, axis=-1, keepdims=True) + 1.0  # + the doc vector
    h = (jnp.sum(ctx * win_mask[..., None], axis=1) + doc) / cnt
    t = syn1[targets]
    n = syn1[negs]
    pos_dot = jnp.sum(h * t, axis=-1)
    neg_dot = jnp.einsum("bd,bkd->bk", h, n)
    loss = -jnp.mean(
        jax.nn.log_sigmoid(pos_dot) + jnp.sum(jax.nn.log_sigmoid(-neg_dot), axis=-1)
    )
    gpos = jax.nn.sigmoid(pos_dot) - 1.0
    gneg = jax.nn.sigmoid(neg_dot)
    d_h = gpos[:, None] * t + jnp.einsum("bk,bkd->bd", gneg, n)   # [B,D]
    d_t = gpos[:, None] * h
    d_n = gneg[..., None] * h[:, None, :]
    d_shared = d_h / cnt
    d_ctx = d_shared[:, None, :] * win_mask[..., None]             # [B,W,D]

    syn0 = syn0.at[context_win.reshape(-1)].add(-lr * d_ctx.reshape(-1, d_ctx.shape[-1]))
    syn0 = syn0.at[doc_ids].add(-lr * d_shared)
    syn1 = syn1.at[targets].add(-lr * d_t)
    syn1 = syn1.at[negs.reshape(-1)].add(-lr * d_n.reshape(-1, d_n.shape[-1]))
    return {"syn0": syn0, "syn1neg": syn1, **{k: v for k, v in params.items()
                                              if k not in ("syn0", "syn1neg")}}, loss


def _cbow_hs_step(params, context_win, win_mask, codes, points, hmask, lr):
    """CBOW hierarchical softmax (CBOW.java HS branch): the window MEAN
    walks the target word's Huffman path.

    context_win/win_mask: [B,W] padded window; codes/points/hmask: [B,L]
    Huffman path of the TARGET word (bit, inner-node idx, validity).
    """
    syn0, syn1 = params["syn0"], params["syn1"]
    ctx = syn0[context_win]                                # [B,W,D]
    cnt = jnp.maximum(jnp.sum(win_mask, axis=-1, keepdims=True), 1.0)
    h = jnp.sum(ctx * win_mask[..., None], axis=1) / cnt   # [B,D]
    w = syn1[points]                                       # [B,L,D]
    dot = jnp.einsum("bd,bld->bl", h, w)
    sign = 1.0 - 2.0 * codes
    loss = -jnp.sum(jax.nn.log_sigmoid(sign * dot) * hmask) / jnp.maximum(
        jnp.sum(hmask), 1.0)
    # dL/ddot of -log sigmoid((1-2c)*dot) is sigmoid(dot) - (1-c): the
    # word2vec label is 1-code (word2vec.c: g = (1 - code - f))
    g = (jax.nn.sigmoid(dot) - (1.0 - codes)) * hmask      # [B,L]
    d_h = jnp.einsum("bl,bld->bd", g, w)
    d_w = g[..., None] * h[:, None, :]
    d_ctx = (d_h / cnt)[:, None, :] * win_mask[..., None]  # [B,W,D]
    syn0 = syn0.at[context_win.reshape(-1)].add(-lr * d_ctx.reshape(-1, d_ctx.shape[-1]))
    syn1 = syn1.at[points.reshape(-1)].add(-lr * d_w.reshape(-1, d_w.shape[-1]))
    return {"syn0": syn0, "syn1": syn1, **{k: v for k, v in params.items()
                                           if k not in ("syn0", "syn1")}}, loss


def _sg_hs_step(params, centers, codes, points, mask, lr):
    """Skip-gram hierarchical softmax over Huffman paths.

    centers [B]; codes/points/mask [B,L] (bit, inner-node idx, validity).
    loss = -Σ log σ((1-2*code) * c·syn1[point]).
    """
    syn0, syn1 = params["syn0"], params["syn1"]
    c = syn0[centers]                                    # [B,D]
    w = syn1[points]                                     # [B,L,D]
    dot = jnp.einsum("bd,bld->bl", c, w)
    sign = 1.0 - 2.0 * codes
    loss = -jnp.sum(jax.nn.log_sigmoid(sign * dot) * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    # word2vec label is 1-code (word2vec.c: g = (1 - code - f)); the prior
    # g = sigmoid-code trained the mirrored convention: embeddings came out
    # isomorphic but the reported loss INCREASED while training
    g = (jax.nn.sigmoid(dot) - (1.0 - codes)) * mask     # [B,L] (w2v's -g)
    d_c = jnp.einsum("bl,bld->bd", g, w)
    d_w = g[..., None] * c[:, None, :]
    syn0 = syn0.at[centers].add(-lr * d_c)
    syn1 = syn1.at[points.reshape(-1)].add(-lr * d_w.reshape(-1, d_w.shape[-1]))
    return {"syn0": syn0, "syn1": syn1, **{k: v for k, v in params.items()
                                           if k not in ("syn0", "syn1")}}, loss


# ---------------------------------------------------------------------------
# host-side pair generation
# ---------------------------------------------------------------------------


class _PairGenerator:
    """Sentence indices → (center, context) pairs with dynamic windows and
    frequent-word subsampling, batched (the role of AsyncSequencer +
    per-thread window loops in the reference)."""

    def __init__(self, window: int, keep_probs: np.ndarray, rs: np.random.RandomState):
        self.window = window
        self.keep = keep_probs
        self.rs = rs

    def generate(self, idx_seqs: Iterable[np.ndarray]):
        for idx in idx_seqs:
            if len(idx) < 2:
                continue
            keep = self.rs.rand(len(idx)) < self.keep[idx]
            idx = idx[keep]
            if len(idx) < 2:
                continue
            b = self.rs.randint(1, self.window + 1, len(idx))
            for i, center in enumerate(idx):
                lo = max(0, i - b[i])
                hi = min(len(idx), i + b[i] + 1)
                for j in range(lo, hi):
                    if j != i:
                        yield center, idx[j]

    def generate_windows(self, idx_seqs: Iterable[np.ndarray]):
        """CBOW windows (CBOW.java semantics): for each center position,
        yield (center, [context ids]) with the full dynamic window — the
        window AVERAGE predicts the center, not reversed skip-gram pairs."""
        for idx in idx_seqs:
            if len(idx) < 2:
                continue
            keep = self.rs.rand(len(idx)) < self.keep[idx]
            idx = idx[keep]
            if len(idx) < 2:
                continue
            b = self.rs.randint(1, self.window + 1, len(idx))
            for i, center in enumerate(idx):
                lo = max(0, i - b[i])
                hi = min(len(idx), i + b[i] + 1)
                ctx = [int(idx[j]) for j in range(lo, hi) if j != i]
                if ctx:
                    yield int(center), ctx


def _batched(gen, batch_size: int):
    buf_c, buf_t = [], []
    for c, t in gen:
        buf_c.append(c)
        buf_t.append(t)
        if len(buf_c) == batch_size:
            yield np.asarray(buf_c, np.int32), np.asarray(buf_t, np.int32)
            buf_c, buf_t = [], []
    if buf_c:
        yield np.asarray(buf_c, np.int32), np.asarray(buf_t, np.int32)


def _fast_pairs(idx_seqs, window: int, keep: np.ndarray,
                rs: np.random.RandomState):
    """Vectorized skip-gram pair generation: per sentence, same
    subsampling + dynamic-window SEMANTICS as _PairGenerator.generate (a
    pair (i, i±o) exists iff o <= b_i and in range) but built with per-
    offset numpy masks instead of a per-pair Python loop — ~50x the
    host-side throughput (docs/PERF.md Word2Vec end-to-end). Draw ORDER
    differs from the per-pair generator, so trajectories are not
    bit-identical across backends (the pair multiset per sentence is,
    given equal rng draws). Yields (centers, contexts) int32 arrays."""
    for idx in idx_seqs:
        if len(idx) < 2:
            continue
        kmask = rs.rand(len(idx)) < keep[idx]
        idx = idx[kmask]
        n = len(idx)
        if n < 2:
            continue
        b = rs.randint(1, window + 1, n)
        pos = np.arange(n)
        cs, ts = [], []
        for o in range(1, window + 1):
            sel = b >= o
            right = pos[sel & (pos + o < n)]
            left = pos[sel & (pos - o >= 0)]
            cs.append(idx[right])
            ts.append(idx[right + o])
            cs.append(idx[left])
            ts.append(idx[left - o])
        yield (np.concatenate(cs).astype(np.int32),
               np.concatenate(ts).astype(np.int32))


def _batched_arrays(gen, batch_size: int):
    """Re-chunk a stream of (centers, contexts) ARRAYS into batch_size
    pieces (array analogue of _batched)."""
    bufs_c, bufs_t, count = [], [], 0
    for c, t in gen:
        bufs_c.append(c)
        bufs_t.append(t)
        count += len(c)
        if count >= batch_size:
            cc = np.concatenate(bufs_c)
            tt = np.concatenate(bufs_t)
            while len(cc) >= batch_size:
                yield cc[:batch_size], tt[:batch_size]
                cc, tt = cc[batch_size:], tt[batch_size:]
            bufs_c, bufs_t, count = [cc], [tt], len(cc)
    if count:
        yield np.concatenate(bufs_c), np.concatenate(bufs_t)


def _batched_windows(gen, batch_size: int, max_width: int):
    """Batch (center, [contexts]) — or tagged (tag, center, [contexts]) —
    into padded [B,W] arrays + win_mask. Tagged items (the PV-DM doc id)
    yield (tags, centers, win, mask); untagged yield (centers, win, mask)."""

    def flush(tags, centers, ctxs):
        B = len(centers)
        win = np.zeros((B, max_width), np.int32)
        mask = np.zeros((B, max_width), np.float32)
        for r, ctx in enumerate(ctxs):
            L = min(len(ctx), max_width)
            win[r, :L] = ctx[:L]
            mask[r, :L] = 1.0
        out = (np.asarray(centers, np.int32), win, mask)
        return (np.asarray(tags, np.int32),) + out if tags else out

    tags, centers, ctxs = [], [], []
    for item in gen:
        if len(item) == 3:
            t, c, ctx = item
            tags.append(t)
        else:
            c, ctx = item
        centers.append(c)
        ctxs.append(ctx)
        if len(centers) == batch_size:
            yield flush(tags, centers, ctxs)
            tags, centers, ctxs = [], [], []
    if centers:
        yield flush(tags, centers, ctxs)


# ---------------------------------------------------------------------------
# SequenceVectors
# ---------------------------------------------------------------------------


class SequenceVectors:
    """Generic embedding trainer over element sequences
    (models/sequencevectors/SequenceVectors.java).

    ``sequences``: iterable of token lists (or a callable producing one per
    epoch). Algorithms: elements_learning = "skipgram" | "cbow";
    use_hierarchic_softmax switches HS on (negative=0) as in the reference.
    """

    def __init__(
        self,
        layer_size: int = 100,
        window: int = 5,
        negative: int = 5,
        use_hierarchic_softmax: bool = False,
        learning_rate: float = 0.025,
        min_learning_rate: float = 1e-4,
        min_word_frequency: int = 5,
        sample: float = 1e-3,
        epochs: int = 1,
        # pairs per fused device step; the step is scatter-add bound at
        # large batches (docs/PERF.md round-4 correction). Raise toward
        # 65536 on big corpora to amortize dispatch.
        batch_size: int = 8192,
        elements_learning: str = "skipgram",
        seed: int = 12345,
        # "python": per-pair generator (reference-faithful draw order);
        # "numpy": vectorized per-offset masks, ~50x host throughput —
        # same pair distribution, different rng draw order (skip-gram only).
        # With "numpy", SG-NS training also runs scan_batches device steps
        # per dispatch (negatives drawn on-device, inverse-CDF over the
        # unigram table — same distribution as the host draw).
        pair_backend: str = "python",
        scan_batches: int = 64,
        # epoch-scan unroll factor: 4 = fastest warm epoch; 1 = ~halved
        # first-epoch XLA compile (see utils/compile_cache for the
        # cross-process amortization alternative)
        scan_unroll: int = 4,
    ):
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.lr = learning_rate
        self.min_lr = min_learning_rate
        self.min_word_frequency = min_word_frequency
        self.sample = sample
        self.epochs = epochs
        self.batch_size = batch_size
        self.elements_learning = elements_learning
        if pair_backend not in ("python", "numpy"):
            raise ValueError(f"pair_backend must be 'python' or 'numpy', got {pair_backend!r}")
        if scan_batches < 1:
            raise ValueError(f"scan_batches must be >= 1, got {scan_batches}")
        if scan_unroll < 1:
            raise ValueError(f"scan_unroll must be >= 1, got {scan_unroll}")
        self.pair_backend = pair_backend
        self.scan_batches = scan_batches
        self.scan_unroll = scan_unroll
        self.seed = seed
        self.vocab: Optional[VocabCache] = None
        self.params: Optional[dict] = None
        self._rs = np.random.RandomState(seed)
        self._step_cache: dict = {}

    # -- vocab + init ------------------------------------------------------
    def build_vocab(self, sequences: Iterable[Sequence[str]], special: Sequence[str] = ()):
        vc = VocabConstructor(self.min_word_frequency, tokenizer=_IdentityTok())
        self.vocab = vc.build(sequences, special=special)
        if self.use_hs:
            build_huffman(self.vocab)
        return self

    def _init_params(self):
        V, D = len(self.vocab), self.layer_size
        rs = np.random.RandomState(self.seed)
        p = {
            "syn0": jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D),
            "syn1neg": jnp.asarray(np.zeros((V, D), np.float32)),
        }
        if self.use_hs:
            p["syn1"] = jnp.asarray(np.zeros((max(V - 1, 1), D), np.float32))
        self.params = p

    # -- training ----------------------------------------------------------
    def _jit_step(self, kind: str):
        if kind not in self._step_cache:
            fn = {"sg_ns": _sg_ns_step, "cbow_ns": _cbow_ns_step,
                  "sg_hs": _sg_hs_step, "cbow_hs": _cbow_hs_step,
                  "dm_ns": _dm_ns_step}[kind]
            self._step_cache[kind] = jax.jit(fn, donate_argnums=(0,))
        return self._step_cache[kind]

    def _index_sequences(self, sequences) -> List[np.ndarray]:
        out = []
        for seq in sequences:
            idx = [self.vocab.index_of(t) for t in seq]
            out.append(np.asarray([i for i in idx if i >= 0], np.int64))
        return out

    def fit(self, sequences) -> "SequenceVectors":
        seqs = sequences() if callable(sequences) else sequences
        seqs = list(seqs)
        if self.vocab is None:
            self.build_vocab(seqs)
        if self.params is None:
            self._init_params()
        self._run_epochs(self._index_sequences(seqs), self.epochs)
        return self

    def _run_epochs(self, idx_seqs, epochs: int, *, schedule_span: Optional[int] = None,
                    schedule_offset: int = 0) -> None:
        """Train ``epochs`` passes over already-indexed sequences against the
        EXISTING vocab/params (the distributed trainer calls this one round
        at a time between parameter-averaging steps).

        ``schedule_span``/``schedule_offset``: total epochs the linear lr
        decay spans and how many are already complete — lets a multi-round
        caller anneal ONCE across all rounds instead of saw-toothing."""
        keep = subsample_probs(self.vocab, self.sample)
        table = unigram_table(self.vocab)
        if self.use_hs:
            codes, points, hmask = huffman_tables(self.vocab)
            codes_j, points_j = jnp.asarray(codes), jnp.asarray(points)
            hmask_j = jnp.asarray(hmask)

        cum_dev = None  # unigram-table cumsum, uploaded once for all epochs
        span = schedule_span if schedule_span is not None else epochs
        pairs_per_epoch = sum(len(s) for s in idx_seqs) * self.window
        total_pairs_est = max(pairs_per_epoch * span, 1)
        seen = pairs_per_epoch * schedule_offset
        for _ in range(epochs):
            pg = _PairGenerator(self.window, keep, self._rs)
            if self.elements_learning == "cbow":
                # true CBOW (CBOW.java): the window AVERAGE predicts the
                # center — padded [B, 2*window] windows with win_mask.
                # NS and HS branches share the window batching; HS walks
                # the CENTER word's Huffman path.
                step = self._jit_step("cbow_hs" if self.use_hs else "cbow_ns")
                for centers, win, wmask in _batched_windows(
                    pg.generate_windows(idx_seqs), self.batch_size, 2 * self.window
                ):
                    frac = min(seen / total_pairs_est, 1.0)
                    lr = max(self.lr * (1.0 - frac), self.min_lr)
                    seen += len(centers)
                    if self.use_hs:
                        self.params, _ = step(
                            self.params, jnp.asarray(win), jnp.asarray(wmask),
                            codes_j[centers], points_j[centers], hmask_j[centers],
                            jnp.asarray(lr, jnp.float32),
                        )
                    else:
                        negs = self._draw_negatives(
                            table, (len(centers), self.negative))
                        self.params, _ = step(
                            self.params, jnp.asarray(win), jnp.asarray(wmask),
                            jnp.asarray(centers), jnp.asarray(negs),
                            jnp.asarray(lr, jnp.float32),
                        )
                continue
            if self.pair_backend == "numpy" and not self.use_hs:
                # epoch-scan fast path: chunks of scan_batches full batches
                # run as ONE device dispatch (lax.scan, on-device negatives)
                # — the leftover tail falls through to the per-batch path
                chunk = self.batch_size * self.scan_batches
                if "sg_ns_scan" not in self._step_cache:
                    self._step_cache["sg_ns_scan"] = jax.jit(
                        _sg_ns_epoch_scan, donate_argnums=(0,),
                        static_argnames=("negative", "unroll"))
                scan_step = self._step_cache["sg_ns_scan"]
                if cum_dev is None:
                    cum_dev = jnp.asarray(np.cumsum(table), jnp.float32)
                cum = cum_dev
                # separate key stream: drawing chunk keys from self._rs
                # would interleave with the (lazy) pair generator's draws
                # and break pair-stream reproducibility
                key_rs = np.random.RandomState(self._rs.randint(2 ** 31))
                tail_c: List[np.ndarray] = []
                tail_t: List[np.ndarray] = []
                for cc, tt in _batched_arrays(
                        _fast_pairs(idx_seqs, self.window, keep, self._rs),
                        chunk):
                    if len(cc) == chunk:
                        key = jax.random.PRNGKey(key_rs.randint(2 ** 31))
                        self.params, _ = scan_step(
                            self.params,
                            jnp.asarray(cc.reshape(self.scan_batches,
                                                   self.batch_size)),
                            jnp.asarray(tt.reshape(self.scan_batches,
                                                   self.batch_size)),
                            cum, key, jnp.asarray(self.lr, jnp.float32),
                            jnp.asarray(self.min_lr, jnp.float32),
                            float(seen), float(total_pairs_est),
                            negative=self.negative,
                            unroll=self.scan_unroll)
                        seen += len(cc)
                    else:
                        tail_c.append(cc)
                        tail_t.append(tt)
                # tail: re-chunk to batch_size for the per-batch path
                pair_stream = _batched_arrays(zip(tail_c, tail_t),
                                              self.batch_size)
            elif self.pair_backend == "numpy":
                pair_stream = _batched_arrays(
                    _fast_pairs(idx_seqs, self.window, keep, self._rs),
                    self.batch_size)
            else:
                pair_stream = _batched(pg.generate(idx_seqs), self.batch_size)
            for centers, contexts in pair_stream:
                frac = min(seen / total_pairs_est, 1.0)
                lr = max(self.lr * (1.0 - frac), self.min_lr)
                seen += len(centers)
                if self.use_hs:
                    step = self._jit_step("sg_hs")
                    self.params, _ = step(
                        self.params, jnp.asarray(centers),
                        codes_j[contexts], points_j[contexts], hmask_j[contexts],
                        jnp.asarray(lr, jnp.float32),
                    )
                else:
                    step = self._jit_step("sg_ns")
                    negs = self._draw_negatives(table, (len(centers), self.negative))
                    self.params, _ = step(
                        self.params, jnp.asarray(centers), jnp.asarray(contexts),
                        jnp.asarray(negs), jnp.asarray(lr, jnp.float32),
                    )

    def _draw_negatives(self, table: np.ndarray, shape) -> np.ndarray:
        # inverse-CDF sampling: identical distribution to
        # rs.choice(p=table) but ~100x faster at vocab 100K (choice-with-p
        # rebuilds its alias structures per call); cumsum cached per table
        cached = getattr(self, "_neg_cum", None)
        if cached is None or cached[0] is not table:
            cached = (table, np.cumsum(table))
            self._neg_cum = cached
        u = self._rs.random_sample(shape)
        return np.minimum(np.searchsorted(cached[1], u),
                          len(table) - 1).astype(np.int32)

    # -- lookup API (WordVectors interface) --------------------------------
    @property
    def syn0(self) -> np.ndarray:
        return np.asarray(self.params["syn0"])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom > 0 else 0.0

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        """Cosine-nearest words — ONE [V,D]x[D] matmul (MXU), not a VP-tree."""
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
            if v is None:
                return []
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        m = self.syn0
        norms = np.linalg.norm(m, axis=1) * max(np.linalg.norm(v), 1e-12)
        sims = (m @ v) / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out


class _IdentityTok:
    def tokenize(self, s):
        return list(s) if not isinstance(s, str) else s.split()


# ---------------------------------------------------------------------------
# Word2Vec / ParagraphVectors / StaticWord2Vec
# ---------------------------------------------------------------------------


class Word2Vec(SequenceVectors):
    """models/word2vec/Word2Vec.java: SequenceVectors over tokenized
    sentences. ``fit(sentences)`` accepts strings or a sentence iterator."""

    def __init__(self, tokenizer_factory=None, **kw):
        super().__init__(**kw)
        self.tokenizer_factory = tokenizer_factory

    def _tokenize_all(self, sentences) -> List[List[str]]:
        from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory

        tok = self.tokenizer_factory or DefaultTokenizerFactory()
        out = []
        for s in sentences:
            out.append(tok.tokenize(s) if isinstance(s, str) else list(s))
        return out

    def build_vocab(self, sentences, special=()):
        return super().build_vocab(self._tokenize_all(sentences), special=special)

    def fit(self, sentences) -> "Word2Vec":
        seqs = sentences() if callable(sentences) else sentences
        return super().fit(self._tokenize_all(seqs))


class ParagraphVectors(Word2Vec):
    """models/paragraphvectors/ParagraphVectors.java: documents get their own
    vectors. ``sequence_learning="dbow"`` (default, the reference's DBOW
    impl: the label vector predicts each word) or ``"dm"`` (PV-DM,
    learning/impl/sequence/DM.java: doc vector + window average predict the
    center word)."""

    LABEL_PREFIX = "__label__"

    def __init__(self, sequence_learning: str = "dbow", **kw):
        kw.setdefault("min_word_frequency", 1)
        super().__init__(**kw)
        if sequence_learning not in ("dbow", "dm"):
            raise ValueError(f"sequence_learning must be 'dbow' or 'dm', "
                             f"got {sequence_learning!r}")
        self.sequence_learning = sequence_learning
        self.labels: List[str] = []

    def fit_documents(self, docs: Sequence[Tuple[str, str]]) -> "ParagraphVectors":
        """docs: (text, label) pairs (LabelAwareIterator surface)."""
        texts = [t for t, _ in docs]
        self.labels = [self.LABEL_PREFIX + l for _, l in docs]
        token_seqs = self._tokenize_all(texts)
        # vocab over words + labels (labels as special tokens)
        super(Word2Vec, self).build_vocab(token_seqs, special=tuple(self.labels))
        self._init_params()
        table = unigram_table(self.vocab)
        if self.sequence_learning == "dm":
            self._fit_dm(token_seqs, table)
        else:
            self._fit_dbow(token_seqs, table)
        # words also train among themselves (reference trainElementsVectors)
        super(Word2Vec, self).fit(token_seqs)
        return self

    def _fit_dbow(self, token_seqs, table):
        # DBOW: every (label, word) pair is a skip-gram pair
        step = self._jit_step("sg_ns")
        lr = self.lr
        for ep in range(self.epochs):
            pairs_c, pairs_t = [], []
            for label, toks in zip(self.labels, token_seqs):
                li = self.vocab.index_of(label)
                for t in toks:
                    ti = self.vocab.index_of(t)
                    if ti >= 0:
                        pairs_c.append(li)
                        pairs_t.append(ti)
            order = self._rs.permutation(len(pairs_c))
            pc = np.asarray(pairs_c, np.int32)[order]
            pt = np.asarray(pairs_t, np.int32)[order]
            for i in range(0, len(pc), self.batch_size):
                c = pc[i:i + self.batch_size]
                t = pt[i:i + self.batch_size]
                negs = self._draw_negatives(table, (len(c), self.negative))
                self.params, _ = step(
                    self.params, jnp.asarray(c), jnp.asarray(t), jnp.asarray(negs),
                    jnp.asarray(lr, jnp.float32),
                )
            lr = max(lr * 0.9, self.min_lr)

    def _fit_dm(self, token_seqs, table):
        # PV-DM: (doc, window) -> center. Windows per document, batched by
        # the shared padded-window batcher with the doc's label row as tag.
        step = self._jit_step("dm_ns")
        keep = subsample_probs(self.vocab, self.sample)
        W = 2 * self.window
        lr = self.lr
        for ep in range(self.epochs):
            pg = _PairGenerator(self.window, keep, self._rs)
            items = []  # (doc_id, center, ctx)
            for label, toks in zip(self.labels, token_seqs):
                li = self.vocab.index_of(label)
                idx = np.asarray(
                    [i for i in (self.vocab.index_of(t) for t in toks) if i >= 0],
                    np.int64)
                for center, ctx in pg.generate_windows([idx]):
                    items.append((li, center, ctx))
            self._rs.shuffle(items)
            for docs, centers, win, mask in _batched_windows(
                    iter(items), self.batch_size, W):
                negs = self._draw_negatives(table, (len(centers), self.negative))
                self.params, _ = step(
                    self.params, jnp.asarray(docs), jnp.asarray(win),
                    jnp.asarray(mask), jnp.asarray(centers), jnp.asarray(negs),
                    jnp.asarray(lr, jnp.float32),
                )
            lr = max(lr * 0.9, self.min_lr)

    def get_label_vector(self, label: str) -> Optional[np.ndarray]:
        return self.get_word_vector(self.LABEL_PREFIX + label)

    def infer_vector(self, text: str, steps: int = 20) -> np.ndarray:
        """Infer a vector for unseen text: average of known word vectors
        refined by DBOW steps against a frozen vocab (inferVector)."""
        toks = self._tokenize_all([text])[0]
        idx = np.asarray([self.vocab.index_of(t) for t in toks], np.int64)
        idx = idx[idx >= 0]
        if len(idx) == 0:
            return np.zeros(self.layer_size, np.float32)
        v = self.syn0[idx].mean(axis=0)
        syn1 = np.asarray(self.params["syn1neg"])
        lr = self.lr
        rs = np.random.RandomState(0)
        table = unigram_table(self.vocab)
        for _ in range(steps):
            for t in idx:
                negs = rs.choice(len(table), size=self.negative, p=table)
                tv = syn1[t]
                g = (1.0 / (1.0 + np.exp(-v @ tv))) - 1.0
                d = g * tv
                for nidx in negs:
                    nv = syn1[nidx]
                    gn = 1.0 / (1.0 + np.exp(-v @ nv))
                    d = d + gn * nv
                v = v - lr * d
            lr *= 0.9
        return v.astype(np.float32)

    def similarity_to_label(self, text: str, label: str) -> float:
        v = self.infer_vector(text)
        lv = self.get_label_vector(label)
        if lv is None:
            return float("nan")
        denom = np.linalg.norm(v) * np.linalg.norm(lv)
        return float(v @ lv / denom) if denom > 0 else 0.0


class StaticWord2Vec:
    """Inference-only word vectors (models/word2vec/StaticWord2Vec.java):
    frozen table + lookup/similarity, no trainer state."""

    def __init__(self, vocab: VocabCache, vectors: np.ndarray):
        self.vocab = vocab
        self.syn0 = np.asarray(vectors, np.float32)

    @staticmethod
    def from_model(m: SequenceVectors) -> "StaticWord2Vec":
        return StaticWord2Vec(m.vocab, m.syn0)

    def get_word_vector(self, word: str):
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom > 0 else 0.0

    def words_nearest(self, word: str, top_n: int = 10) -> List[str]:
        sv = SequenceVectors.__new__(SequenceVectors)
        sv.vocab = self.vocab
        sv.params = {"syn0": jnp.asarray(self.syn0)}
        return SequenceVectors.words_nearest(sv, word, top_n)
