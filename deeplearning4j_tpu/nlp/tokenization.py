"""Tokenizers, token preprocessors, sentence/document iterators.

Capability parity with the reference's text pipeline
(deeplearning4j-nlp-parent/deeplearning4j-nlp/.../text/: tokenization/
tokenizerfactory/DefaultTokenizerFactory, NGramTokenizerFactory,
tokenization/tokenizer/preprocessor/CommonPreprocessor,
sentenceiterator/{BasicLineIterator,CollectionSentenceIterator,
FileSentenceIterator}, documentiterator/LabelAwareIterator — SURVEY.md §2.7
'Text pipeline' row). Host-side text handling; the TPU sees only index
arrays.
"""

from __future__ import annotations

import os
import re
import string
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple


class CommonPreprocessor:
    """Lowercase + strip punctuation (preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()

    __call__ = pre_process


class LowCasePreprocessor:
    def pre_process(self, token: str) -> str:
        return token.lower()

    __call__ = pre_process


class DefaultTokenizer:
    """Whitespace tokenizer with optional per-token preprocessor
    (tokenizer/DefaultTokenizer.java)."""

    def __init__(self, text: str, pre: Optional[Callable] = None):
        self._tokens = [t for t in text.split() if t]
        if pre is not None:
            self._tokens = [p for p in (pre(t) for t in self._tokens) if p]

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def __iter__(self):
        return iter(self._tokens)


class DefaultTokenizerFactory:
    """tokenizerfactory/DefaultTokenizerFactory.java."""

    def __init__(self):
        self._pre: Optional[Callable] = None

    def set_token_pre_processor(self, pre: Callable):
        self._pre = pre
        return self

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self._pre)

    def tokenize(self, text: str) -> List[str]:
        return self.create(text).get_tokens()


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """n-gram over the base tokens (NGramTokenizerFactory.java)."""

    def __init__(self, min_n: int = 1, max_n: int = 2):
        super().__init__()
        self.min_n, self.max_n = min_n, max_n

    def tokenize(self, text: str) -> List[str]:
        base = super().tokenize(text)
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                out.append(" ".join(base[i:i + n]))
        return out


# -- sentence / document iterators ------------------------------------------

class CollectionSentenceIterator:
    """In-memory list of sentences (CollectionSentenceIterator.java)."""

    def __init__(self, sentences: Sequence[str]):
        self.sentences = list(sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self.sentences)

    def reset(self):
        pass


class BasicLineIterator:
    """One sentence per line from a file (BasicLineIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[str]:
        with open(self.path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line

    def reset(self):
        pass


class FileSentenceIterator:
    """Every file under a directory, one sentence per line
    (FileSentenceIterator.java)."""

    def __init__(self, root: str):
        self.root = root

    def __iter__(self) -> Iterator[str]:
        for dirpath, _, files in sorted(os.walk(self.root)):
            for fn in sorted(files):
                yield from BasicLineIterator(os.path.join(dirpath, fn))

    def reset(self):
        pass


class LabelledDocument:
    """documentiterator/LabelledDocument.java."""

    def __init__(self, content: str, labels: Sequence[str]):
        self.content = content
        self.labels = list(labels)


class LabelAwareIterator:
    """Documents with labels, for ParagraphVectors
    (documentiterator/LabelAwareIterator.java). Wraps (text, label) pairs."""

    def __init__(self, docs: Sequence[Tuple[str, str]]):
        self.docs = [LabelledDocument(t, [l]) for t, l in docs]

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self.docs)

    def reset(self):
        pass
