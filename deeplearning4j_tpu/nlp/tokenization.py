"""Tokenizers, token preprocessors, sentence/document iterators.

Capability parity with the reference's text pipeline
(deeplearning4j-nlp-parent/deeplearning4j-nlp/.../text/: tokenization/
tokenizerfactory/DefaultTokenizerFactory, NGramTokenizerFactory,
tokenization/tokenizer/preprocessor/CommonPreprocessor,
sentenceiterator/{BasicLineIterator,CollectionSentenceIterator,
FileSentenceIterator}, documentiterator/LabelAwareIterator — SURVEY.md §2.7
'Text pipeline' row). Host-side text handling; the TPU sees only index
arrays.
"""

from __future__ import annotations

import os
import re
import string
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple


class CommonPreprocessor:
    """Lowercase + strip punctuation (preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()

    __call__ = pre_process


class LowCasePreprocessor:
    def pre_process(self, token: str) -> str:
        return token.lower()

    __call__ = pre_process


class DefaultTokenizer:
    """Whitespace tokenizer with optional per-token preprocessor
    (tokenizer/DefaultTokenizer.java)."""

    def __init__(self, text: str, pre: Optional[Callable] = None):
        self._tokens = [t for t in text.split() if t]
        if pre is not None:
            self._tokens = [p for p in (pre(t) for t in self._tokens) if p]

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def __iter__(self):
        return iter(self._tokens)


class DefaultTokenizerFactory:
    """tokenizerfactory/DefaultTokenizerFactory.java."""

    def __init__(self):
        self._pre: Optional[Callable] = None

    def set_token_pre_processor(self, pre: Callable):
        self._pre = pre
        return self

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self._pre)

    def tokenize(self, text: str) -> List[str]:
        return self.create(text).get_tokens()


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """n-gram over the base tokens (NGramTokenizerFactory.java)."""

    def __init__(self, min_n: int = 1, max_n: int = 2):
        super().__init__()
        self.min_n, self.max_n = min_n, max_n

    def tokenize(self, text: str) -> List[str]:
        base = super().tokenize(text)
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                out.append(" ".join(base[i:i + n]))
        return out


# -- sentence / document iterators ------------------------------------------

class CollectionSentenceIterator:
    """In-memory list of sentences (CollectionSentenceIterator.java)."""

    def __init__(self, sentences: Sequence[str]):
        self.sentences = list(sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self.sentences)

    def reset(self):
        pass


class BasicLineIterator:
    """One sentence per line from a file (BasicLineIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[str]:
        with open(self.path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line

    def reset(self):
        pass


class FileSentenceIterator:
    """Every file under a directory, one sentence per line
    (FileSentenceIterator.java)."""

    def __init__(self, root: str):
        self.root = root

    def __iter__(self) -> Iterator[str]:
        for dirpath, _, files in sorted(os.walk(self.root)):
            for fn in sorted(files):
                yield from BasicLineIterator(os.path.join(dirpath, fn))

    def reset(self):
        pass


class LabelledDocument:
    """documentiterator/LabelledDocument.java."""

    def __init__(self, content: str, labels: Sequence[str]):
        self.content = content
        self.labels = list(labels)


class LabelAwareIterator:
    """Documents with labels, for ParagraphVectors
    (documentiterator/LabelAwareIterator.java). Wraps (text, label) pairs."""

    def __init__(self, docs: Sequence[Tuple[str, str]]):
        self.docs = [LabelledDocument(t, [l]) for t, l in docs]

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self.docs)

    def reset(self):
        pass


# ---------------------------------------------------------------------------
# CJK tokenization (deeplearning4j-nlp-chinese / -japanese / -korean)
# ---------------------------------------------------------------------------

_CJK_RANGES = (
    (0x4E00, 0x9FFF),    # CJK Unified Ideographs
    (0x3400, 0x4DBF),    # CJK Extension A
    (0xF900, 0xFAFF),    # CJK Compatibility Ideographs
    (0x3040, 0x309F),    # Hiragana
    (0x30A0, 0x30FF),    # Katakana
    (0xAC00, 0xD7AF),    # Hangul Syllables
    (0x1100, 0x11FF),    # Hangul Jamo
    (0x20000, 0x3FFFF),  # supplementary-plane ideographs (Ext B-G + compat)
)


def _is_cjk(ch: str) -> bool:
    cp = ord(ch)
    return any(lo <= cp <= hi for lo, hi in _CJK_RANGES)


class CJKTokenizer:
    """Dictionary-free CJK segmentation by character bigrams.

    Scope stand-in for the reference's bundled third-party analyzers
    (deeplearning4j-nlp-chinese: ansj ~9.5K LoC, -japanese: kuromoji ~6.8K
    LoC, -korean glue): those embed dictionary-driven morphological
    analysis this framework deliberately does not vendor (README
    "Deliberate descopes"). The overlapping-bigram scheme here is the
    classic dictionary-free IR fallback (Lucene CJKAnalyzer): embedding
    quality on CJK corpora is serviceable, morphology is not attempted.
    Latin/digit runs inside CJK text are kept as whole tokens; a true
    morphological analyzer can be plugged in as a ``tokenizer_factory``.
    """

    def __init__(self, text: str, preprocessor: Optional[Callable[[str], str]] = None):
        self._tokens: List[str] = []
        run: List[str] = []      # pending CJK character run
        word: List[str] = []     # pending non-CJK word run

        def flush_run():
            if len(run) == 1:
                self._tokens.append(run[0])
            else:
                self._tokens.extend(run[i] + run[i + 1]
                                    for i in range(len(run) - 1))
            run.clear()

        def flush_word():
            if word:
                self._tokens.append("".join(word))
                word.clear()

        for ch in text:
            if _is_cjk(ch):
                flush_word()
                run.append(ch)
            elif ch.isalnum():
                if run:
                    flush_run()
                word.append(ch)
            else:
                flush_word()
                if run:
                    flush_run()
        flush_word()
        if run:
            flush_run()
        if preprocessor is not None:
            self._tokens = [t for t in (preprocessor(t) for t in self._tokens) if t]
        self._i = 0

    def has_more_tokens(self) -> bool:
        return self._i < len(self._tokens)

    def next_token(self) -> str:
        t = self._tokens[self._i]
        self._i += 1
        return t

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)


class CJKTokenizerFactory:
    """TokenizerFactory over :class:`CJKTokenizer` (the reference's
    ChineseTokenizerFactory / JapaneseTokenizerFactory surface)."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor

    def set_token_pre_processor(self, pre: Callable):
        """Same factory surface as DefaultTokenizerFactory — the two are
        drop-in interchangeable."""
        self.preprocessor = pre
        return self

    def create(self, text: str) -> CJKTokenizer:
        return CJKTokenizer(text, self.preprocessor)

    def tokenize(self, text: str) -> List[str]:
        return self.create(text).get_tokens()
