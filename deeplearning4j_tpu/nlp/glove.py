"""GloVe embeddings.

Capability parity with the reference's GloVe learning impl
(models/embeddings/learning/impl/elements/GloVe.java + models/glove/ —
SURVEY.md §2.7). TPU-first: the co-occurrence matrix builds host-side (it
is a string-processing pass, like the reference's co-occurrence pipeline);
training runs as jitted AdaGrad steps over BATCHES of nonzero (i, j, X_ij)
triples — gathers, the weighted-least-squares loss, and scatter updates in
one XLA program per batch.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


def _glove_step(params, wi, wj, xij, lr, x_max, alpha):
    """One AdaGrad batch: J = Σ f(X) (w_i·w̃_j + b_i + b̃_j - log X)²."""
    W, Wc, b, bc = params["W"], params["Wc"], params["b"], params["bc"]
    hW, hWc, hb, hbc = params["hW"], params["hWc"], params["hb"], params["hbc"]

    vi = W[wi]
    vj = Wc[wj]
    diff = jnp.sum(vi * vj, axis=-1) + b[wi] + bc[wj] - jnp.log(xij)
    f = jnp.minimum((xij / x_max) ** alpha, 1.0)
    loss = 0.5 * jnp.mean(f * diff * diff)

    g = f * diff                                  # [B]
    gW = g[:, None] * vj
    gWc = g[:, None] * vi

    # AdaGrad accumulate + update (scatter)
    hW = hW.at[wi].add(gW * gW)
    hWc = hWc.at[wj].add(gWc * gWc)
    hb = hb.at[wi].add(g * g)
    hbc = hbc.at[wj].add(g * g)
    W = W.at[wi].add(-lr * gW / jnp.sqrt(hW[wi] + 1e-8))
    Wc = Wc.at[wj].add(-lr * gWc / jnp.sqrt(hWc[wj] + 1e-8))
    b = b.at[wi].add(-lr * g / jnp.sqrt(hb[wi] + 1e-8))
    bc = bc.at[wj].add(-lr * g / jnp.sqrt(hbc[wj] + 1e-8))
    return {"W": W, "Wc": Wc, "b": b, "bc": bc,
            "hW": hW, "hWc": hWc, "hb": hb, "hbc": hbc}, loss


class Glove:
    """models/glove/Glove.java surface: build co-occurrences, fit, lookup."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 learning_rate: float = 0.05, epochs: int = 5,
                 min_word_frequency: int = 1, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 1024, seed: int = 12345,
                 symmetric: bool = True, tokenizer_factory=None):
        self.layer_size = layer_size
        self.window = window
        self.lr = learning_rate
        self.epochs = epochs
        self.min_word_frequency = min_word_frequency
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.seed = seed
        self.symmetric = symmetric
        self.tokenizer_factory = tokenizer_factory
        self.vocab: Optional[VocabCache] = None
        self.params: Optional[dict] = None

    def _tokenize(self, sentences) -> List[List[str]]:
        from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory

        tok = self.tokenizer_factory or DefaultTokenizerFactory()
        return [tok.tokenize(s) if isinstance(s, str) else list(s) for s in sentences]

    def _cooccurrences(self, token_seqs) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        counts: Dict[Tuple[int, int], float] = defaultdict(float)
        for toks in token_seqs:
            idx = [self.vocab.index_of(t) for t in toks]
            idx = [i for i in idx if i >= 0]
            for i, wi in enumerate(idx):
                for off in range(1, self.window + 1):
                    j = i + off
                    if j >= len(idx):
                        break
                    w = 1.0 / off  # distance weighting, as GloVe does
                    counts[(wi, idx[j])] += w
                    if self.symmetric:
                        counts[(idx[j], wi)] += w
        ii = np.asarray([k[0] for k in counts], np.int32)
        jj = np.asarray([k[1] for k in counts], np.int32)
        xx = np.asarray(list(counts.values()), np.float32)
        return ii, jj, xx

    def fit(self, sentences) -> "Glove":
        token_seqs = self._tokenize(sentences() if callable(sentences) else sentences)
        if self.vocab is None:
            self.vocab = VocabConstructor(self.min_word_frequency).build(
                [" ".join(t) for t in token_seqs]
            )
        V, D = len(self.vocab), self.layer_size
        rs = np.random.RandomState(self.seed)
        self.params = {
            "W": jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D),
            "Wc": jnp.asarray((rs.rand(V, D).astype(np.float32) - 0.5) / D),
            "b": jnp.zeros((V,), jnp.float32),
            "bc": jnp.zeros((V,), jnp.float32),
            "hW": jnp.full((V, D), 1e-8, jnp.float32),
            "hWc": jnp.full((V, D), 1e-8, jnp.float32),
            "hb": jnp.full((V,), 1e-8, jnp.float32),
            "hbc": jnp.full((V,), 1e-8, jnp.float32),
        }
        ii, jj, xx = self._cooccurrences(token_seqs)
        if len(ii) == 0:
            return self
        step = jax.jit(_glove_step, donate_argnums=(0,),
                       static_argnames=("x_max", "alpha"))
        for _ in range(self.epochs):
            order = rs.permutation(len(ii))
            for s in range(0, len(order), self.batch_size):
                sel = order[s:s + self.batch_size]
                self.params, _ = step(
                    self.params, jnp.asarray(ii[sel]), jnp.asarray(jj[sel]),
                    jnp.asarray(xx[sel]), jnp.asarray(self.lr, jnp.float32),
                    x_max=self.x_max, alpha=self.alpha,
                )
        return self

    # -- lookup ------------------------------------------------------------
    @property
    def syn0(self) -> np.ndarray:
        return np.asarray(self.params["W"]) + np.asarray(self.params["Wc"])

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom > 0 else 0.0
