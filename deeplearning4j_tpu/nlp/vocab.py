"""Vocabulary construction + Huffman coding.

Capability parity with the reference's vocab machinery
(models/word2vec/wordstore/VocabConstructor.java:31 buildJointVocabulary:167,
wordstore/inmemory/AbstractCache, models/word2vec/VocabWord,
models/word2vec/Huffman.java — SURVEY.md §2.7). Counting is host-side (it is
IO-bound string work); the output is index arrays + Huffman code tables the
jitted trainers consume.
"""

from __future__ import annotations

import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class VocabWord:
    """models/word2vec/VocabWord.java: word + frequency + Huffman code."""

    __slots__ = ("word", "count", "index", "code", "points")

    def __init__(self, word: str, count: int = 1, index: int = -1):
        self.word = word
        self.count = count
        self.index = index
        self.code: List[int] = []     # Huffman bits (0/1)
        self.points: List[int] = []   # inner-node indices on the root path

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count}, idx={self.index})"


class VocabCache:
    """In-memory vocab store (wordstore/inmemory/AbstractCache.java)."""

    def __init__(self):
        self.words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_word_count = 0

    def add(self, vw: VocabWord):
        vw.index = len(self.words)
        self.words.append(vw)
        self._by_word[vw.word] = vw

    def __contains__(self, word: str) -> bool:
        return word in self._by_word

    def __len__(self) -> int:
        return len(self.words)

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._by_word.get(word)

    def index_of(self, word: str) -> int:
        vw = self._by_word.get(word)
        return vw.index if vw is not None else -1

    def word_at(self, index: int) -> str:
        return self.words[index].word

    def counts(self) -> np.ndarray:
        return np.asarray([w.count for w in self.words], np.float64)


class VocabConstructor:
    """Count tokens over sentence iterables, apply min_word_frequency, sort
    by frequency (VocabConstructor.buildJointVocabulary:167)."""

    def __init__(self, min_word_frequency: int = 5, tokenizer=None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer = tokenizer

    def build(self, sentences: Iterable, special: Sequence[str] = ()) -> VocabCache:
        from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory

        tok = self.tokenizer or DefaultTokenizerFactory()
        counts: Counter = Counter()
        total = 0
        for s in sentences:
            toks = tok.tokenize(s) if isinstance(s, str) else list(s)
            counts.update(toks)
            total += len(toks)
        cache = VocabCache()
        for w in special:  # labels/special tokens survive min-frequency
            cache.add(VocabWord(w, counts.pop(w, 1)))
        for w, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if c >= self.min_word_frequency:
                cache.add(VocabWord(w, c))
        cache.total_word_count = total
        return cache


def build_huffman(cache: VocabCache, max_code_length: int = 40):
    """Assign Huffman codes/points to every vocab word
    (models/word2vec/Huffman.java). Inner nodes are numbered 0..V-2; the
    root path is stored leaf→root REVERSED to root→leaf, as word2vec does."""
    V = len(cache)
    if V == 0:
        return
    if V == 1:
        cache.words[0].code = [0]
        cache.words[0].points = [0]
        return
    heap: List = []
    for i, w in enumerate(cache.words):
        heapq.heappush(heap, (w.count, i, None))
    next_inner = 0
    parent: Dict[int, tuple] = {}  # node id -> (parent_inner_idx, bit)
    # node ids: leaves 0..V-1, inner nodes V..2V-2 (inner index = id - V)
    nid = V
    while len(heap) > 1:
        c1, id1, _ = heapq.heappop(heap)
        c2, id2, _ = heapq.heappop(heap)
        inner_idx = nid - V
        parent[id1] = (nid, 0)
        parent[id2] = (nid, 1)
        heapq.heappush(heap, (c1 + c2, nid, None))
        nid += 1
    root_id = heap[0][1]
    for i, w in enumerate(cache.words):
        code: List[int] = []
        points: List[int] = []
        node = i
        while node != root_id:
            pid, bit = parent[node]
            code.append(bit)
            points.append(pid - V)
            node = pid
        w.code = list(reversed(code))[:max_code_length]
        w.points = list(reversed(points))[:max_code_length]


def huffman_tables(cache: VocabCache, max_len: Optional[int] = None):
    """Pack codes/points into padded arrays for the jitted HS trainer:
    (codes [V,L], points [V,L], mask [V,L])."""
    if not cache.words or not cache.words[0].code:
        build_huffman(cache)
    L = max_len or max(len(w.code) for w in cache.words)
    V = len(cache)
    codes = np.zeros((V, L), np.float32)
    points = np.zeros((V, L), np.int32)
    mask = np.zeros((V, L), np.float32)
    for i, w in enumerate(cache.words):
        n = min(len(w.code), L)
        codes[i, :n] = w.code[:n]
        points[i, :n] = w.points[:n]
        mask[i, :n] = 1.0
    return codes, points, mask


def unigram_table(cache: VocabCache, power: float = 0.75) -> np.ndarray:
    """Negative-sampling distribution: counts^0.75 normalized (the word2vec
    unigram table, used by SkipGram.java negative sampling)."""
    p = cache.counts() ** power
    return (p / p.sum()).astype(np.float64)


def subsample_probs(cache: VocabCache, sample: float = 1e-3) -> np.ndarray:
    """Per-word KEEP probability under frequent-word subsampling
    (word2vec's subsampling formula)."""
    if sample <= 0:
        return np.ones(len(cache), np.float64)
    freq = cache.counts() / max(cache.total_word_count, 1)
    keep = np.sqrt(sample / np.maximum(freq, 1e-12)) + sample / np.maximum(freq, 1e-12)
    return np.clip(keep, 0.0, 1.0)
