"""Bag-of-words / TF-IDF vectorizers.

Parity: bagofwords/vectorizer/ (BagOfWordsVectorizer, TfidfVectorizer:
fit over a corpus builds the vocab + document frequencies;
transform(document) -> vector; vectorize(text, label) -> DataSet). The
reference runs per-document Java loops; here transform of a batch is a
single [n_docs, V] count matrix built host-side then any model math on
device.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BagOfWordsVectorizer:
    """Count vectors over a fitted vocab."""

    def __init__(self, min_word_frequency: int = 1, tokenizer=None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer = tokenizer
        self.vocab: Optional[VocabCache] = None

    def _tokenize(self, doc) -> List[str]:
        if isinstance(doc, str):
            if self.tokenizer is not None:
                return self.tokenizer.tokenize(doc)
            return doc.split()
        return list(doc)

    def fit(self, docs: Iterable) -> "BagOfWordsVectorizer":
        token_docs = [self._tokenize(d) for d in docs]
        vc = VocabConstructor(self.min_word_frequency, tokenizer=_Identity())
        self.vocab = vc.build(token_docs)
        self._post_fit(token_docs)
        return self

    def _post_fit(self, token_docs: List[List[str]]) -> None:
        pass

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) if self.vocab else 0

    def transform(self, docs) -> np.ndarray:
        """docs: one document or a sequence -> [n_docs, V] float32."""
        if isinstance(docs, str):
            docs = [docs]
        out = np.zeros((len(docs), self.vocab_size), np.float32)
        for i, d in enumerate(docs):
            for t in self._tokenize(d):
                j = self.vocab.index_of(t)
                if j >= 0:
                    out[i, j] += 1.0
        return self._weight(out)

    def _weight(self, counts: np.ndarray) -> np.ndarray:
        return counts

    def fit_transform(self, docs: Sequence) -> np.ndarray:
        self.fit(docs)
        return self.transform(list(docs))

    def vectorize(self, text: str, label: str, labels: Sequence[str]):
        """(features, one-hot label) pair — the reference's
        vectorize(text, label) -> DataSet surface."""
        x = self.transform([text])[0]
        y = np.zeros(len(labels), np.float32)
        y[list(labels).index(label)] = 1.0
        return x, y


class TfidfVectorizer(BagOfWordsVectorizer):
    """tf-idf weighting: tf * log(N / df) (TfidfVectorizer.java's
    formulation; smooth=True uses log((1+N)/(1+df)) + 1)."""

    def __init__(self, min_word_frequency: int = 1, tokenizer=None,
                 smooth: bool = True):
        super().__init__(min_word_frequency, tokenizer)
        self.smooth = smooth
        self.idf: Optional[np.ndarray] = None

    def _post_fit(self, token_docs: List[List[str]]) -> None:
        n_docs = len(token_docs)
        df = np.zeros(self.vocab_size, np.float64)
        for toks in token_docs:
            for j in {self.vocab.index_of(t) for t in toks}:
                if j >= 0:
                    df[j] += 1.0
        if self.smooth:
            self.idf = (np.log((1.0 + n_docs) / (1.0 + df)) + 1.0).astype(np.float32)
        else:
            self.idf = np.log(np.maximum(n_docs / np.maximum(df, 1.0), 1.0)).astype(np.float32)

    def _weight(self, counts: np.ndarray) -> np.ndarray:
        return counts * self.idf[None, :]


class _Identity:
    def tokenize(self, s):
        return list(s) if not isinstance(s, str) else s.split()
