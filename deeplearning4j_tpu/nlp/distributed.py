"""Distributed Word2Vec over jax.distributed processes.

Capability parity with the reference's Spark NLP scaleout
(deeplearning4j-scaleout/spark/dl4j-spark-nlp: SparkWord2Vec — distributed
vocabulary construction at the driver + parameter-averaged training rounds).
TPU-native redesign: there is no driver. Every process holds a corpus shard;

1. **Distributed vocab build**: local token counts are serialized to bytes
   and exchanged with ``jax.experimental.multihost_utils.process_allgather``
   (two phases: lengths, then padded payloads), merged identically on every
   process — all hosts end with the SAME vocab (word order included).
2. **Parameter-averaged rounds**: each round runs local epochs with the
   fused negative-sampling steps (nlp/embeddings.py), then syn0/syn1 are
   averaged across processes (the Spark master's averaging step, exact).

Single-process mode degrades to plain Word2Vec.fit (the averaging is a
no-op), so the same code serves both paths.
"""

from __future__ import annotations

import json
from typing import List, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.embeddings import Word2Vec
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord, build_huffman


def _allgather_objects(obj) -> List[dict]:
    """Exchange one JSON-serializable object per process; returns every
    process's object (same order everywhere). Single-process: [obj]."""
    import jax

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils as mhu

    payload = np.frombuffer(json.dumps(obj).encode("utf-8"), np.uint8)
    lengths = np.asarray(mhu.process_allgather(
        np.asarray([payload.size], np.int32)))
    max_len = int(lengths.max())
    padded = np.zeros(max_len, np.uint8)
    padded[:payload.size] = payload
    gathered = np.asarray(mhu.process_allgather(padded))
    out = []
    for row, n in zip(gathered.reshape(-1, max_len), lengths.ravel()):
        out.append(json.loads(bytes(row[:int(n)]).decode("utf-8")))
    return out


class DistributedWord2Vec(Word2Vec):
    """Word2Vec whose fit() spans every jax.distributed process.

    ``rounds``: parameter-averaging rounds; each runs ``epochs_per_round``
    local epochs (total work ≈ rounds * epochs_per_round per shard).
    """

    def __init__(self, rounds: int = 1, epochs_per_round: int = 1, **kw):
        if "epochs" in kw:
            raise ValueError(
                "DistributedWord2Vec: pass rounds=/epochs_per_round= instead "
                "of epochs= (total epochs = rounds * epochs_per_round)")
        kw["epochs"] = rounds * epochs_per_round
        super().__init__(**kw)
        self.rounds = rounds
        self.epochs_per_round = epochs_per_round

    # -- distributed vocab -------------------------------------------------
    def build_vocab_distributed(self, local_token_seqs: Sequence[Sequence[str]]):
        from collections import Counter

        counts: Counter = Counter()
        total = 0
        for toks in local_token_seqs:
            counts.update(toks)
            total += len(toks)
        merged: Counter = Counter()
        g_total = 0
        for remote in _allgather_objects(
                {"counts": dict(counts), "total": total}):
            merged.update(remote["counts"])
            g_total += remote["total"]
        cache = VocabCache()
        for w, c in sorted(merged.items(), key=lambda kv: (-kv[1], kv[0])):
            if c >= self.min_word_frequency:
                cache.add(VocabWord(w, c))
        cache.total_word_count = g_total
        self.vocab = cache
        if self.use_hs:
            build_huffman(self.vocab)
        return self

    # -- parameter averaging ----------------------------------------------
    def _average_params(self):
        import jax

        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils as mhu

        new = {}
        for k, v in self.params.items():
            gathered = np.asarray(mhu.process_allgather(np.asarray(v)))
            new[k] = np.mean(gathered, axis=0).astype(np.float32)
        import jax.numpy as jnp

        self.params = {k: jnp.asarray(v) for k, v in new.items()}

    # -- training ----------------------------------------------------------
    def fit(self, local_sentences) -> "DistributedWord2Vec":
        """``local_sentences``: THIS process's shard of the corpus."""
        seqs = local_sentences() if callable(local_sentences) else local_sentences
        token_seqs = self._tokenize_all(seqs)
        if self.vocab is None:
            self.build_vocab_distributed(token_seqs)
        if self.params is None:
            self._init_params()   # same seed everywhere -> identical init
        idx_seqs = self._index_sequences(token_seqs)
        span = self.rounds * self.epochs_per_round
        for r in range(self.rounds):
            # the lr anneals ONCE across all rounds (not per round)
            self._run_epochs(idx_seqs, self.epochs_per_round,
                             schedule_span=span,
                             schedule_offset=r * self.epochs_per_round)
            self._average_params()
        return self
