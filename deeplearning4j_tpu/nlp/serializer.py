"""Word-vector serialization: text, word2vec-binary, and zip formats.

Parity: models/embeddings/loader/WordVectorSerializer.java
(writeWordVectors / loadTxtVectors -> text "word v1 v2 ...";
readBinaryModel/writeBinary -> the original word2vec .bin layout
"V D\\n" + per-word "word " + D float32s; writeWord2VecModel zip with
vocab + vectors). Trained embeddings can leave the process in formats the
original word2vec / gensim / the reference all read.
"""

from __future__ import annotations

import json
import struct
import zipfile
from typing import TYPE_CHECKING, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord

if TYPE_CHECKING:  # pragma: no cover
    from deeplearning4j_tpu.nlp.embeddings import SequenceVectors, StaticWord2Vec


def _vocab_and_vectors(model) -> Tuple[VocabCache, np.ndarray]:
    vocab = model.vocab
    vectors = np.asarray(model.syn0, np.float32)
    if vocab is None or len(vocab) != vectors.shape[0]:
        raise ValueError("model has no vocab or vocab/vector size mismatch")
    return vocab, vectors


class WordVectorSerializer:
    # -- text format -------------------------------------------------------
    @staticmethod
    def write_word_vectors(model, path: str) -> None:
        """One line per word: ``word v1 v2 ... vD`` (writeWordVectors)."""
        vocab, vectors = _vocab_and_vectors(model)
        with open(path, "w", encoding="utf-8") as f:
            for i in range(len(vocab)):
                vec = " ".join(f"{v:.6g}" for v in vectors[i])
                f.write(f"{vocab.word_at(i)} {vec}\n")

    @staticmethod
    def load_txt_vectors(path: str) -> "StaticWord2Vec":
        """Reads text format (with or without a leading "V D" header line)."""
        from deeplearning4j_tpu.nlp.embeddings import StaticWord2Vec

        words, rows = [], []
        with open(path, encoding="utf-8") as f:
            first = f.readline().rstrip("\n")
            parts = first.split(" ")
            if len(parts) == 2 and all(p.isdigit() for p in parts):
                pass  # header line, skip
            elif parts:
                words.append(parts[0])
                rows.append([float(v) for v in parts[1:]])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                rows.append([float(v) for v in parts[1:]])
        vocab = VocabCache()
        for w in words:
            vocab.add(VocabWord(w))
        return StaticWord2Vec(vocab, np.asarray(rows, np.float32))

    # -- word2vec binary ---------------------------------------------------
    @staticmethod
    def write_binary(model, path: str) -> None:
        """Original word2vec .bin layout (readBinaryModel's inverse)."""
        vocab, vectors = _vocab_and_vectors(model)
        V, D = vectors.shape
        with open(path, "wb") as f:
            f.write(f"{V} {D}\n".encode("utf-8"))
            for i in range(V):
                f.write(vocab.word_at(i).encode("utf-8") + b" ")
                f.write(vectors[i].astype("<f4").tobytes())
                f.write(b"\n")

    @staticmethod
    def read_binary(path: str) -> "StaticWord2Vec":
        from deeplearning4j_tpu.nlp.embeddings import StaticWord2Vec

        with open(path, "rb") as f:
            header = b""
            while not header.endswith(b"\n"):
                header += f.read(1)
            V, D = (int(t) for t in header.decode("utf-8").split())
            vocab = VocabCache()
            vectors = np.empty((V, D), np.float32)
            for i in range(V):
                word = b""
                while True:
                    c = f.read(1)
                    if c in (b" ", b""):
                        break
                    if c != b"\n":  # leading newline from previous row
                        word += c
                vocab.add(VocabWord(word.decode("utf-8")))
                vectors[i] = np.frombuffer(f.read(4 * D), dtype="<f4")
        return StaticWord2Vec(vocab, vectors)

    # -- zip container -----------------------------------------------------
    @staticmethod
    def write_word2vec_model(model, path: str) -> None:
        """Zip with vectors.bin + vocab.json (+ counts), the
        writeWord2VecModel container capability."""
        vocab, vectors = _vocab_and_vectors(model)
        meta = {
            "format": "deeplearning4j_tpu/word2vec",
            "version": 1,
            "vocab": [
                {"word": vocab.word_at(i), "count": int(vocab.word_for(vocab.word_at(i)).count)}
                for i in range(len(vocab))
            ],
            "layer_size": int(vectors.shape[1]),
        }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("metadata.json", json.dumps(meta))
            z.writestr("syn0.npy", _npy_bytes(vectors))

    @staticmethod
    def read_word2vec_model(path: str) -> "StaticWord2Vec":
        from deeplearning4j_tpu.nlp.embeddings import StaticWord2Vec
        import io

        with zipfile.ZipFile(path) as z:
            meta = json.loads(z.read("metadata.json"))
            vectors = np.load(io.BytesIO(z.read("syn0.npy")))
        vocab = VocabCache()
        for entry in meta["vocab"]:
            vocab.add(VocabWord(entry["word"], count=entry.get("count", 1)))
        return StaticWord2Vec(vocab, vectors)


def _npy_bytes(arr: np.ndarray) -> bytes:
    import io

    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()
