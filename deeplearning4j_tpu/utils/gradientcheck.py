"""Numerical-vs-analytic gradient checking harness.

Parity: gradientcheck/GradientCheckUtil.java:109 (MLN), :331 (graph) — the
correctness backbone of the reference's test suite (16 gradient-check suites,
SURVEY.md §4). Central-difference perturbation in float64 against jax.grad
of the model's loss.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:
    _enable_x64 = jax.enable_x64  # jax >= 0.5
except AttributeError:  # jax 0.4.x keeps it under experimental
    from jax.experimental import enable_x64 as _enable_x64


def check_gradients(
    model,
    x,
    y,
    fmask=None,
    lmask=None,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    subset: Optional[int] = None,
    seed: int = 12345,
    print_results: bool = False,
) -> bool:
    """Central-difference check of d(loss)/d(params) for a MultiLayerNetwork
    or ComputationGraph (anything exposing ``_loss``-style via ``loss_for_check``).

    ``subset``: check only N randomly chosen parameters per tensor (the
    reference checks all; sub-sampling keeps CI fast for big nets).
    """
    with _enable_x64(True):
        def to64(t):
            if t is None:
                return None
            return jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a), jnp.float64), t
            )

        params64 = to64(model.params)
        state64 = to64(model.state)
        # x/y may be tuples of arrays (ComputationGraph multi-input/output)
        x, y, fm, lm = to64(x), to64(y), to64(fmask), to64(lmask)

        def loss_fn(p):
            loss, _ = model._loss(p, state64, x, y, fm, lm, rngs=None, train=False)
            return loss

        analytic = jax.grad(loss_fn)(params64)

        flat_p, treedef = jax.tree_util.tree_flatten(params64)
        flat_g = jax.tree_util.tree_leaves(analytic)
        rng = np.random.RandomState(seed)
        n_fail = 0
        n_checked = 0
        max_err = 0.0

        for ti, (p, g) in enumerate(zip(flat_p, flat_g)):
            pn = np.array(p, np.float64)  # writable copy
            gn = np.asarray(g, np.float64)
            size = pn.size
            if subset is not None and size > subset:
                idxs = rng.choice(size, subset, replace=False)
            else:
                idxs = np.arange(size)
            for flat_idx in idxs:
                orig = pn.flat[flat_idx]
                pn.flat[flat_idx] = orig + epsilon
                flat_p[ti] = jnp.asarray(pn)
                plus = float(loss_fn(jax.tree_util.tree_unflatten(treedef, flat_p)))
                pn.flat[flat_idx] = orig - epsilon
                flat_p[ti] = jnp.asarray(pn)
                minus = float(loss_fn(jax.tree_util.tree_unflatten(treedef, flat_p)))
                pn.flat[flat_idx] = orig
                flat_p[ti] = jnp.asarray(pn)

                numeric = (plus - minus) / (2 * epsilon)
                a = gn.flat[flat_idx]
                denom = abs(a) + abs(numeric)
                rel = abs(a - numeric) / denom if denom > 0 else 0.0
                n_checked += 1
                if rel > max_rel_error and abs(a - numeric) > min_abs_error:
                    n_fail += 1
                    if print_results:
                        print(f"FAIL tensor {ti} idx {flat_idx}: analytic={a:.8g} "
                              f"numeric={numeric:.8g} rel={rel:.4g}")
                max_err = max(max_err, rel if abs(a - numeric) > min_abs_error else 0.0)

        if print_results:
            print(f"Gradient check: {n_checked - n_fail}/{n_checked} passed, "
                  f"max rel error {max_err:.4g}")
        return n_fail == 0
