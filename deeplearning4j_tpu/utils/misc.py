"""Misc reference utilities: Viterbi label smoother, MovingWindowMatrix.

Reference: deeplearning4j-nn util/Viterbi.java and util/MovingWindowMatrix.java
(§2.1 "misc util" tail). The reference Viterbi is a noisy-channel label
SMOOTHER: observed per-frame labels are treated as emissions of a hidden
state chain whose self-transitions are sticky (``meta_stability``) and whose
emissions are correct with ``p_correct`` — decoding yields a de-noised label
sequence. NOTE: the reference implementation never fills its backpointer
matrix (Viterbi.java:82-106), so its backtrace returns zeros; this
implementation is the intended, correct DP (documented divergence)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


class Viterbi:
    """``Viterbi(n_states).decode(labels)`` -> (best_log_prob, smoothed).

    ``labels``: [T] int outcomes or [T, K] one-hot/probability rows (argmax
    is taken, Viterbi.java's toOutcomesFromBinaryLabelMatrix)."""

    def __init__(self, states: int, meta_stability: float = 0.9,
                 p_correct: float = 0.99):
        if states < 2:
            raise ValueError("Viterbi needs >= 2 states")
        if not (0.5 < meta_stability < 1.0) or not (0.5 < p_correct < 1.0):
            raise ValueError("meta_stability and p_correct must be in (0.5, 1)")
        self.states = int(states)
        self.meta_stability = float(meta_stability)
        self.p_correct = float(p_correct)
        K = self.states
        self._log_trans = np.full((K, K), np.log((1.0 - meta_stability) / (K - 1)))
        np.fill_diagonal(self._log_trans, np.log(meta_stability))
        self._log_emit_hit = np.log(p_correct)
        self._log_emit_miss = np.log((1.0 - p_correct) / (K - 1))

    def _outcomes(self, labels) -> np.ndarray:
        a = np.asarray(labels)
        if a.ndim == 2:
            return np.argmax(a, axis=1).astype(np.int64)
        return a.astype(np.int64)

    def decode(self, labels) -> Tuple[float, np.ndarray]:
        obs = self._outcomes(labels)
        T, K = len(obs), self.states
        if T == 0:
            return 0.0, obs
        if (obs < 0).any() or (obs >= K).any():
            raise ValueError(f"labels out of range [0, {K})")
        emit = np.full((T, K), self._log_emit_miss)
        emit[np.arange(T), obs] = self._log_emit_hit
        V = np.empty((T, K))
        ptr = np.zeros((T, K), np.int64)
        V[0] = emit[0] - np.log(K)          # uniform prior
        for t in range(1, T):
            scores = V[t - 1][:, None] + self._log_trans   # [from, to]
            ptr[t] = np.argmax(scores, axis=0)
            V[t] = scores[ptr[t], np.arange(K)] + emit[t]
        path = np.empty(T, np.int64)
        path[-1] = int(np.argmax(V[-1]))
        for t in range(T - 2, -1, -1):
            path[t] = ptr[t + 1, path[t + 1]]
        return float(V[-1].max()), path


class MovingWindowMatrix:
    """Sliding sub-windows of a 2-D matrix (MovingWindowMatrix.java):
    ``window_list()`` returns every (rows x cols) window at stride 1, with
    optional 90/180/270-degree rotations appended (``add_rotate``)."""

    def __init__(self, to_slice, window_rows: int = 28, window_cols: int = 28,
                 add_rotate: bool = False):
        self.m = np.asarray(to_slice)
        if self.m.ndim != 2:
            raise ValueError("MovingWindowMatrix expects a 2-D matrix")
        if window_rows > self.m.shape[0] or window_cols > self.m.shape[1]:
            raise ValueError(
                f"window {window_rows}x{window_cols} exceeds matrix "
                f"{self.m.shape}")
        self.window_rows = int(window_rows)
        self.window_cols = int(window_cols)
        self.add_rotate = bool(add_rotate)

    def window_list(self):
        H, W = self.m.shape
        out = []
        for i in range(H - self.window_rows + 1):
            for j in range(W - self.window_cols + 1):
                w = self.m[i:i + self.window_rows, j:j + self.window_cols]
                out.append(w.copy())
                if self.add_rotate:
                    for k in (1, 2, 3):
                        out.append(np.rot90(w, k).copy())
        return out
