"""Utilities: model serialization, gradient checking."""

from deeplearning4j_tpu.utils.serialization import (
    restore_network,
    save_network,
)

__all__ = ["save_network", "restore_network"]
