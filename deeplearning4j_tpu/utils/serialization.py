"""Model checkpointing: zip container with JSON config + weight arrays.

Parity: util/ModelSerializer.java (entry names configuration.json /
coefficients.bin / updaterState.bin, writeModel:51-127,
restoreMultiLayerNetwork) — the same capability (one portable file holding
config + params + optimizer state + step counters) with npz tensors instead
of a flattened binary view. The JSON config inside the zip is the long-lived
artifact the reference regression-tests across releases (SURVEY.md §4).

Durability (train/resilience.py): path targets are written atomically —
tmp file in the destination directory + fsync + ``os.replace`` + directory
fsync — so a kill mid-save leaves either the previous checkpoint or the new
one, never a torn file. Full-state checkpoints add ``trainState.json`` (RNG
key, batch-in-epoch position, LR scale, telemetry snapshot) and
``residuals.npz`` (PR-3 data-parallel compression residuals); both are
optional entries, so older zips restore unchanged.

No pickle anywhere: configs are JSON, tensors are npz — a checkpoint from an
untrusted source cannot execute code on load.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Optional

import jax
import numpy as np

CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.npz"
STATE_ENTRY = "state.npz"
UPDATER_ENTRY = "updaterState.npz"
META_ENTRY = "meta.json"
NORMALIZER_ENTRY = "normalizer.json"
TRAIN_STATE_ENTRY = "trainState.json"
RESIDUALS_ENTRY = "residuals.npz"


def _tree_to_npz_bytes(tree) -> bytes:
    leaves = jax.tree_util.tree_leaves(tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
    return buf.getvalue()


def _npz_bytes_to_leaves(data: bytes):
    with np.load(io.BytesIO(data)) as z:
        return [z[f"leaf_{i}"] for i in range(len(z.files))]


def _restore_tree_like(template, leaves):
    flat, treedef = jax.tree_util.tree_flatten(template)
    if len(flat) != len(leaves):
        raise ValueError(
            f"Checkpoint has {len(leaves)} arrays but model expects {len(flat)} — "
            "config/checkpoint mismatch"
        )
    import jax.numpy as jnp

    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(l).astype(f.dtype).reshape(f.shape) for l, f in zip(leaves, flat)]
    )


def _atomic_write_zip(path, write_entries) -> None:
    """Write a zip durably: tmp in the same directory, fsync the file, swap
    it in with ``os.replace``, then fsync the directory so the rename itself
    survives a crash (the checkpointInfo.json index uses the same dance)."""
    target = os.fspath(path)
    directory = os.path.dirname(os.path.abspath(target))
    tmp = target + ".tmp"
    with open(tmp, "wb") as f:
        with zipfile.ZipFile(f, "w", zipfile.ZIP_DEFLATED) as zf:
            write_entries(zf)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, target)
    dfd = os.open(directory or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def save_network(model, path, save_updater: bool = True,
                 normalizer: Optional[dict] = None,
                 train_state: Optional[dict] = None,
                 residuals: Optional[dict] = None,
                 opt_state=None):
    """Write a model (MultiLayerNetwork or ComputationGraph) to a zip.

    ``train_state``/``residuals`` add the full-state entries (see
    train/resilience.py); ``opt_state`` overrides ``model.opt_state`` for the
    updater entry (a DataParallelStep snapshots its flat exchange layout back
    to the structured form mid-fit). Path targets are written atomically;
    file-like targets are written directly."""
    meta = {
        "framework": "deeplearning4j_tpu",
        "format_version": 1,
        "iteration": model.iteration,
        "epoch": getattr(model, "epoch", 0),
        "model_class": type(model).__name__,
    }
    opt = model.opt_state if opt_state is None else opt_state

    def write_entries(zf):
        zf.writestr(CONFIG_ENTRY, model.conf.to_json(indent=2))
        zf.writestr(COEFFICIENTS_ENTRY, _tree_to_npz_bytes(model.params))
        zf.writestr(STATE_ENTRY, _tree_to_npz_bytes(model.state))
        if save_updater and opt is not None:
            zf.writestr(UPDATER_ENTRY, _tree_to_npz_bytes(opt))
        if normalizer is not None:
            zf.writestr(NORMALIZER_ENTRY, json.dumps(normalizer))
        if train_state is not None:
            zf.writestr(TRAIN_STATE_ENTRY, json.dumps(train_state))
        if residuals is not None:
            buf = io.BytesIO()
            np.savez(buf, **{k: np.asarray(v) for k, v in residuals.items()})
            zf.writestr(RESIDUALS_ENTRY, buf.getvalue())
        zf.writestr(META_ENTRY, json.dumps(meta))

    if isinstance(path, (str, os.PathLike)):
        _atomic_write_zip(path, write_entries)
    else:
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            write_entries(zf)
    return path


def read_snapshot(path, load_updater: bool = True) -> dict:
    """Read every entry of a checkpoint zip into plain host data (no model
    construction): config dict, meta, leaf lists, and the optional
    train-state/residual extras."""
    with zipfile.ZipFile(path, "r") as zf:
        names = set(zf.namelist())
        snap = {
            "conf": json.loads(zf.read(CONFIG_ENTRY)),
            "meta": json.loads(zf.read(META_ENTRY)) if META_ENTRY in names else {},
            "coeff": _npz_bytes_to_leaves(zf.read(COEFFICIENTS_ENTRY)),
            "state": (
                _npz_bytes_to_leaves(zf.read(STATE_ENTRY)) if STATE_ENTRY in names else None
            ),
            "upd": (
                _npz_bytes_to_leaves(zf.read(UPDATER_ENTRY))
                if load_updater and UPDATER_ENTRY in names
                else None
            ),
            "train_state": (
                json.loads(zf.read(TRAIN_STATE_ENTRY)) if TRAIN_STATE_ENTRY in names else None
            ),
            "residuals": None,
        }
        if RESIDUALS_ENTRY in names:
            with np.load(io.BytesIO(zf.read(RESIDUALS_ENTRY))) as z:
                snap["residuals"] = {k: z[k] for k in z.files}
    return snap


def apply_snapshot(model, snap: dict, load_updater: bool = True):
    """Apply a :func:`read_snapshot` result onto an initialized model:
    params/state/opt trees, iteration/epoch, and — when present — the
    train-state extras (RNG key, batch position, LR scale) and pending DP
    residuals (picked up by the next DataParallelStep ``begin()``)."""
    model.params = _restore_tree_like(model.params, snap["coeff"])
    if snap["state"] is not None:
        model.state = _restore_tree_like(model.state, snap["state"])
    if load_updater and snap["upd"] is not None:
        model.opt_state = _restore_tree_like(model.opt_state, snap["upd"])
    meta = snap["meta"]
    model.iteration = meta.get("iteration", 0)
    model.epoch = meta.get("epoch", 0)
    ts = snap.get("train_state")
    if ts:
        _apply_train_state(model, ts)
    model._pending_residuals = snap.get("residuals")
    # Barrier: the restored leaves are fresh host->device transfers about to
    # enter a donate_argnums step chain; materialize them before the first
    # step can reuse their buffers (async dispatch + donation race).
    import jax

    jax.block_until_ready(  # graftlint: disable=host-sync
        (model.params, model.state, model.opt_state))
    return model


def _apply_train_state(model, ts: dict) -> None:
    import jax.numpy as jnp

    rng = ts.get("rng")
    if rng is not None and getattr(model, "_rng", None) is not None:
        model._rng = jnp.asarray(
            np.asarray(rng, dtype=ts.get("rng_dtype", "uint32")))
    model.batch_in_epoch = int(ts.get("batch_in_epoch", 0))
    scale = float(ts.get("lr_scale", 1.0))
    prev = float(getattr(model, "_lr_scale", 1.0))
    model._lr_scale = scale
    if scale != prev and hasattr(model, "_build_updaters"):
        model._build_updaters()
        if hasattr(model, "_clear_compiled"):
            model._clear_compiled()


def restore_network(path, load_updater: bool = True):
    """Restore a model saved by :func:`save_network`. Dispatches on the config
    format tag (ModelGuesser-style: one entry point for either model class)."""
    snap = read_snapshot(path, load_updater=load_updater)
    conf_json = snap["conf"]

    fmt = conf_json.get("format", "")
    if fmt.endswith("ComputationGraphConfiguration"):
        from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration

        conf = ComputationGraphConfiguration.from_dict(conf_json)
        model = ComputationGraph(conf).init()
    else:
        from deeplearning4j_tpu.nn.model import MultiLayerConfiguration, MultiLayerNetwork

        conf = MultiLayerConfiguration.from_dict(conf_json)
        model = MultiLayerNetwork(conf).init()

    return apply_snapshot(model, snap, load_updater=load_updater)


def restore_normalizer(path) -> Optional[dict]:
    with zipfile.ZipFile(path, "r") as zf:
        if NORMALIZER_ENTRY in zf.namelist():
            return json.loads(zf.read(NORMALIZER_ENTRY))
    return None
