"""Model checkpointing: zip container with JSON config + weight arrays.

Parity: util/ModelSerializer.java (entry names configuration.json /
coefficients.bin / updaterState.bin, writeModel:51-127,
restoreMultiLayerNetwork) — the same capability (one portable file holding
config + params + optimizer state + step counters) with npz tensors instead
of a flattened binary view. The JSON config inside the zip is the long-lived
artifact the reference regression-tests across releases (SURVEY.md §4).

No pickle anywhere: configs are JSON, tensors are npz — a checkpoint from an
untrusted source cannot execute code on load.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import jax
import numpy as np

CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.npz"
STATE_ENTRY = "state.npz"
UPDATER_ENTRY = "updaterState.npz"
META_ENTRY = "meta.json"
NORMALIZER_ENTRY = "normalizer.json"


def _tree_to_npz_bytes(tree) -> bytes:
    leaves = jax.tree_util.tree_leaves(tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
    return buf.getvalue()


def _npz_bytes_to_leaves(data: bytes):
    with np.load(io.BytesIO(data)) as z:
        return [z[f"leaf_{i}"] for i in range(len(z.files))]


def _restore_tree_like(template, leaves):
    flat, treedef = jax.tree_util.tree_flatten(template)
    if len(flat) != len(leaves):
        raise ValueError(
            f"Checkpoint has {len(leaves)} arrays but model expects {len(flat)} — "
            "config/checkpoint mismatch"
        )
    import jax.numpy as jnp

    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(l).astype(f.dtype).reshape(f.shape) for l, f in zip(leaves, flat)]
    )


def save_network(model, path, save_updater: bool = True, normalizer: Optional[dict] = None):
    """Write a model (MultiLayerNetwork or ComputationGraph) to a zip."""
    meta = {
        "framework": "deeplearning4j_tpu",
        "format_version": 1,
        "iteration": model.iteration,
        "epoch": getattr(model, "epoch", 0),
        "model_class": type(model).__name__,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(CONFIG_ENTRY, model.conf.to_json(indent=2))
        zf.writestr(COEFFICIENTS_ENTRY, _tree_to_npz_bytes(model.params))
        zf.writestr(STATE_ENTRY, _tree_to_npz_bytes(model.state))
        if save_updater and model.opt_state is not None:
            zf.writestr(UPDATER_ENTRY, _tree_to_npz_bytes(model.opt_state))
        if normalizer is not None:
            zf.writestr(NORMALIZER_ENTRY, json.dumps(normalizer))
        zf.writestr(META_ENTRY, json.dumps(meta))
    return path


def restore_network(path, load_updater: bool = True):
    """Restore a model saved by :func:`save_network`. Dispatches on the config
    format tag (ModelGuesser-style: one entry point for either model class)."""
    with zipfile.ZipFile(path, "r") as zf:
        conf_json = json.loads(zf.read(CONFIG_ENTRY))
        meta = json.loads(zf.read(META_ENTRY)) if META_ENTRY in zf.namelist() else {}
        coeff = _npz_bytes_to_leaves(zf.read(COEFFICIENTS_ENTRY))
        state = (
            _npz_bytes_to_leaves(zf.read(STATE_ENTRY)) if STATE_ENTRY in zf.namelist() else None
        )
        upd = (
            _npz_bytes_to_leaves(zf.read(UPDATER_ENTRY))
            if load_updater and UPDATER_ENTRY in zf.namelist()
            else None
        )

    fmt = conf_json.get("format", "")
    if fmt.endswith("ComputationGraphConfiguration"):
        from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration

        conf = ComputationGraphConfiguration.from_dict(conf_json)
        model = ComputationGraph(conf).init()
    else:
        from deeplearning4j_tpu.nn.model import MultiLayerConfiguration, MultiLayerNetwork

        conf = MultiLayerConfiguration.from_dict(conf_json)
        model = MultiLayerNetwork(conf).init()

    model.params = _restore_tree_like(model.params, coeff)
    if state is not None:
        model.state = _restore_tree_like(model.state, state)
    if upd is not None:
        model.opt_state = _restore_tree_like(model.opt_state, upd)
    model.iteration = meta.get("iteration", 0)
    model.epoch = meta.get("epoch", 0)
    return model


def restore_normalizer(path) -> Optional[dict]:
    with zipfile.ZipFile(path, "r") as zf:
        if NORMALIZER_ENTRY in zf.namelist():
            return json.loads(zf.read(NORMALIZER_ENTRY))
    return None
