"""ModelGuesser: "load whatever file this is".

Reference parity: deeplearning4j-core util/ModelGuesser.java (loadModelGuess
tries MultiLayerNetwork restore, ComputationGraph restore, Keras import, then
the bare config JSONs). Extended here with the DL4J zip dialect, since this
framework's native zip and the reference's zip share neither layout nor
binary format.

Order of attempts:
  1. native zip (utils/serialization.restore_network — handles both MLN & CG)
  2. reference DL4J zip (modelimport/dl4j.import_dl4j_zip)
  3. Keras HDF5 (modelimport/keras.KerasModelImport)
  4. config JSON (MultiLayerConfiguration / ComputationGraphConfiguration —
     returns the CONFIG, uninitalized, like ModelGuesser.loadConfigGuess)
"""

from __future__ import annotations

import json
import zipfile


def load_any(path: str):
    """Load a model (or bare configuration) from any supported file format.
    Raises ValueError listing every attempt if nothing matches."""
    errors = []

    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
        if "meta.json" in names or any(n.endswith(".npz") for n in names):
            try:
                from deeplearning4j_tpu.utils.serialization import restore_network
                return restore_network(path)
            except Exception as e:  # fall through to the DL4J dialect
                errors.append(f"native zip: {type(e).__name__}: {e}")
        if "configuration.json" in names:
            try:
                from deeplearning4j_tpu.modelimport.dl4j import import_dl4j_zip
                return import_dl4j_zip(path)
            except Exception as e:
                errors.append(f"DL4J zip: {type(e).__name__}: {e}")
    else:
        errors.append("not a zip")

    try:
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        return KerasModelImport.import_keras_model(path)
    except Exception as e:
        errors.append(f"keras h5: {type(e).__name__}: {e}")

    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        fmt = str(d.get("format", ""))
        if fmt.endswith("ComputationGraphConfiguration"):
            from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
            return ComputationGraphConfiguration.from_dict(d)
        if fmt.endswith("MultiLayerConfiguration"):
            from deeplearning4j_tpu.nn.model import MultiLayerConfiguration
            return MultiLayerConfiguration.from_dict(d)
        errors.append(f"json: unknown format tag {fmt!r}")
    except Exception as e:
        errors.append(f"config json: {type(e).__name__}: {e}")

    raise ValueError(f"load_any({path!r}): no loader succeeded — " + "; ".join(errors))
