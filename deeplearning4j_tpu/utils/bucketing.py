"""Shape-bucketed execution: one XLA executable per bucket, not per batch size.

Every distinct batch shape that reaches a jitted step compiles a fresh XLA
executable; irregular serving traffic and partial final fit() batches
therefore pay a compile per distinct request size. μ-cuDNN (PAPERS.md) shows
batch-size canonicalization is the lever that keeps a fixed kernel set hot —
the same applies to XLA compile caches. This module is the shared subsystem:

- A geometric **bucket ladder** (``BucketLadder`` / ``bucket_size``): round a
  batch's leading dimension up to the next rung so mixed sizes collapse onto
  a small fixed set of compiled shapes.
- **Padding helpers** that emit the per-example validity weights the
  loss/BatchNorm paths already honor (``pad_fit_batch``/``pad_fit_multi``:
  tiled rows + zero example-weight + a pre-scaled label mask so the loss
  equals the mean over the real rows EXACTLY — same mechanism as
  ParallelWrapper's DP padding), plus zero-padding for row-independent
  inference (``pad_rows_zero``) and ``unpad`` to slice results back.
- Optional **time-axis bucketing** for RNN/sequence inputs (``pad_time``):
  pad T up a rung and extend/synthesize the feature mask so padded steps are
  ignored by mask-honoring layers.
- A process-wide **telemetry counter** (``telemetry()``): jitted callers
  record a trace event from inside the traced python body (which runs once
  per compile) and a bucket-hit event per call, so compile-vs-traffic ratios
  are observable in benchmarks and asserted in tests.

Env knobs (read per call, so tests can flip them; values that reached a jit
are baked into already-compiled executables as shapes, not re-read):

- ``DL4J_TPU_BUCKETING``       master switch for all wired paths (default 1)
- ``DL4J_TPU_BUCKETS``         explicit ascending ladder, e.g. "8,16,32,64";
                               sizes beyond the top rung keep growing
                               geometrically from it
- ``DL4J_TPU_BUCKET_MIN``      smallest rung of the geometric ladder (default 1)
- ``DL4J_TPU_BUCKET_GROWTH``   ladder growth factor (default 2.0, must be >1)
"""

from __future__ import annotations

import math
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "BucketLadder",
    "BucketTelemetry",
    "bucketing_enabled",
    "bucket_size",
    "ladder_from_env",
    "pad_fit_batch",
    "pad_fit_multi",
    "pad_rows_zero",
    "pad_time",
    "padded_label_mask",
    "telemetry",
    "tile_pad",
    "unpad",
]


# ---------------------------------------------------------------------------
# Ladder
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BucketLadder:
    """Ascending bucket rungs. ``rungs`` may be an explicit list; beyond the
    top rung (or with no explicit rungs) sizes grow geometrically by
    ``growth`` starting at ``min_size``/the top rung, so the ladder covers
    any batch size with O(log n) distinct executables."""

    rungs: Tuple[int, ...] = ()
    min_size: int = 1
    growth: float = 2.0

    def __post_init__(self):
        if self.min_size < 1:
            raise ValueError(f"bucket min_size must be >= 1, got {self.min_size}")
        if self.growth <= 1.0:
            raise ValueError(f"bucket growth must be > 1, got {self.growth}")
        if any(b <= a for a, b in zip(self.rungs, self.rungs[1:])):
            raise ValueError(f"bucket rungs must be strictly ascending, got {self.rungs}")

    def bucket(self, n: int) -> int:
        """Smallest rung >= n."""
        if n <= 0:
            return n
        for r in self.rungs:
            if n <= r:
                return r
        top = self.rungs[-1] if self.rungs else self.min_size
        while top < n:
            top = max(top + 1, int(math.ceil(top * self.growth)))
        return top


def ladder_from_env() -> BucketLadder:
    """Ladder from the DL4J_TPU_BUCKET* env knobs (parsed per call — cheap —
    with clear errors naming the variable)."""
    raw = os.environ.get("DL4J_TPU_BUCKETS")
    rungs: Tuple[int, ...] = ()
    if raw:
        try:
            rungs = tuple(int(tok) for tok in raw.split(",") if tok.strip())
        except ValueError:
            raise ValueError(
                f"DL4J_TPU_BUCKETS must be comma-separated integers, got {raw!r}")
    try:
        min_size = int(os.environ.get("DL4J_TPU_BUCKET_MIN", "1"))
    except ValueError:
        raise ValueError(
            "DL4J_TPU_BUCKET_MIN must be an integer, got "
            f"{os.environ.get('DL4J_TPU_BUCKET_MIN')!r}")
    try:
        growth = float(os.environ.get("DL4J_TPU_BUCKET_GROWTH", "2.0"))
    except ValueError:
        raise ValueError(
            "DL4J_TPU_BUCKET_GROWTH must be a number, got "
            f"{os.environ.get('DL4J_TPU_BUCKET_GROWTH')!r}")
    return BucketLadder(rungs=rungs, min_size=min_size, growth=growth)


def bucketing_enabled() -> bool:
    return os.environ.get("DL4J_TPU_BUCKETING", "1") != "0"


def bucket_size(n: int, ladder: Optional[BucketLadder] = None) -> int:
    """Round ``n`` up to its bucket on ``ladder`` (env ladder by default)."""
    return (ladder or ladder_from_env()).bucket(n)


# ---------------------------------------------------------------------------
# Telemetry
# ---------------------------------------------------------------------------


class BucketTelemetry:
    """Process-wide compile/bucket-hit counters (thread-safe: the
    ParallelInference worker and fit loops record concurrently).

    ``record_trace`` is called from INSIDE jitted python bodies — the body
    runs once per distinct input signature, so ``traces[site]`` counts actual
    traces/compiles, not calls. ``record_hit`` counts one padded dispatch.

    Since PR 5 this class is an **adapter shim** over the obs metrics
    registry (``deeplearning4j_tpu/obs/``): the counters live in registry
    families (``dl4j_bucketing_*``, ``dl4j_comm_bytes``,
    ``dl4j_guard_events_total``) so they are scrapeable at /metrics, while
    every pre-existing accessor (``traces``, ``bucket_hits``, ``comm``,
    ``guard_events``, ``snapshot()``, ...) keeps its exact shape. The
    process singleton (``telemetry()``) shares the process registry and
    emits trace / bucket-promotion events; ad-hoc instances get a private
    registry so tests can't cross-talk."""

    def __init__(self, registry=None, emit_events: bool = False):
        from deeplearning4j_tpu.obs import metrics as _obs_metrics

        self._lock = threading.Lock()
        self._emit_events = emit_events
        reg = registry if registry is not None else _obs_metrics.MetricsRegistry()
        self._traces = reg.counter(
            "dl4j_bucketing_traces_total",
            "XLA traces/compiles by jitted site (recorded inside traced "
            "bodies, so this counts compiles, not calls)", ("site",))
        # the public compile counter (docs/OBSERVABILITY.md): same increment
        # as the legacy bucketing family above, under the name dashboards and
        # the cold_start bench key on — zero delta across a request window
        # proves the request hit only pre-compiled executables
        self._compiles = reg.counter(
            "dl4j_compiles_total",
            "XLA compiles by jitted site (every trace of a jitted body, "
            "lazy or AOT — see dl4j_aot_warm_hits_total for AOT dispatch "
            "hits)", ("site",))
        self._hits = reg.counter(
            "dl4j_bucketing_hits_total",
            "padded dispatches by site and bucket rung", ("site", "bucket"))
        self._padded = reg.counter(
            "dl4j_bucketing_padded_examples_total",
            "padding waste: rows added to reach bucket rungs")
        self._real = reg.counter(
            "dl4j_bucketing_real_examples_total",
            "real rows dispatched through bucketed paths")
        self._comm = reg.gauge(
            "dl4j_comm_bytes",
            "per-step collective bytes by exchange site (dense = hypothetical "
            "dense all-reduce, wire = configured exchange, param = sharded-"
            "update all-gather); describes a configuration, latest wins",
            ("site", "kind"))
        self._guard = reg.counter(
            "dl4j_guard_events_total",
            "divergence-guard events (invalid_score, warn/skip_batch/"
            "rollback trips, rollback_restore)", ("event",))
        self.trace_shapes: Dict[str, set] = {}

    def reset(self):
        with self._lock:
            for fam in (self._traces, self._compiles, self._hits,
                        self._padded, self._real, self._comm, self._guard):
                fam.clear()
            self.trace_shapes = {}

    def record_trace(self, site: str, shape: Sequence[int]):
        with self._lock:
            self.trace_shapes.setdefault(site, set()).add(tuple(shape))
        self._compiles.inc(site=site)
        count = self._traces.inc(site=site)
        # flag the site for lazy cost harvest (obs/profile.py): a set add,
        # no jax — runs inside the traced body exactly once per compile
        from deeplearning4j_tpu.obs import profile

        profile.note_trace(site, shape)
        if self._emit_events:
            from deeplearning4j_tpu import obs

            obs.event("trace", site=site, shape=list(shape), compiles=int(count))

    def record_hit(self, site: str, n: int, bucket: int):
        first = self._hits.inc(site=site, bucket=bucket) == 1
        self._real.inc(n)
        self._padded.inc(max(bucket - n, 0))
        if first and self._emit_events:
            from deeplearning4j_tpu import obs

            obs.event("bucket_promotion", site=site, bucket=int(bucket))

    def record_comm(self, site: str, dense_bytes: int, wire_bytes: int,
                    param_bytes: int = 0):
        """Record a site's PER-STEP collective byte accounting (static shape
        arithmetic, recorded when a DataParallelStep plan is built):
        ``dense_bytes`` = what a dense all-reduce of the exchanged gradients
        would move, ``wire_bytes`` = what the configured exchange moves,
        ``param_bytes`` = sharded-update's extra updated-param all-gather.
        Latest values win — the numbers describe a configuration, not a
        running total."""
        self._comm.set(int(dense_bytes), site=site, kind="dense_bytes")
        self._comm.set(int(wire_bytes), site=site, kind="wire_bytes")
        self._comm.set(int(param_bytes), site=site, kind="param_bytes")

    def record_guard(self, event: str):
        """Count one divergence-guard event (``invalid_score``, a policy trip
        ``warn``/``skip_batch``/``rollback``, or ``rollback_restore``) — the
        InvalidScoreIterationTerminationCondition-style counters surfaced in
        snapshots (train/resilience.py)."""
        self._guard.inc(event=event)

    # -- pre-obs accessors (shim views over the registry families) ---------

    @property
    def traces(self) -> Dict[str, int]:
        return {k[0]: int(v) for k, v in self._traces.as_dict().items()}

    @property
    def bucket_hits(self) -> Dict[Tuple[str, int], int]:
        return {(k[0], int(k[1])): int(v)
                for k, v in self._hits.as_dict().items()}

    @property
    def padded_examples(self) -> int:
        return int(self._padded.value())

    @property
    def real_examples(self) -> int:
        return int(self._real.value())

    @property
    def comm(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for (site, kind), v in self._comm.as_dict().items():
            out.setdefault(site, {})[kind] = int(v)
        return out

    @property
    def guard_events(self) -> Dict[str, int]:
        return {k[0]: int(v) for k, v in self._guard.as_dict().items()}

    def compiles(self, site: Optional[str] = None) -> int:
        if site is not None:
            return int(self._traces.value(site=site))
        return sum(self.traces.values())

    def buckets_used(self, site: Optional[str] = None) -> Tuple[int, ...]:
        return tuple(sorted({int(b) for (s, b) in self._hits.as_dict()
                             if site is None or s == site}))

    def snapshot(self) -> dict:
        """JSON-friendly view for bench extras."""
        return {
            "traces": self.traces,
            "bucket_hits": {f"{s}:{b}": c
                            for (s, b), c in sorted(self.bucket_hits.items())},
            "padded_examples": self.padded_examples,
            "real_examples": self.real_examples,
            "comm": self.comm,
            "guard": self.guard_events,
        }


def _process_telemetry() -> BucketTelemetry:
    from deeplearning4j_tpu.obs import metrics as _obs_metrics

    return BucketTelemetry(registry=_obs_metrics.registry(), emit_events=True)


_TELEMETRY = _process_telemetry()


def telemetry() -> BucketTelemetry:
    return _TELEMETRY


# ---------------------------------------------------------------------------
# Padding / unpadding
# ---------------------------------------------------------------------------


def tile_pad(a, pad: int):
    """Append ``pad`` rows to ``a`` by tiling its real rows (zero rows when
    the array is empty). Tiled rows keep batch-coupled numerics benign; the
    caller must zero-weight them in the loss."""
    if a is None:
        return None
    a = np.asarray(a)
    if len(a) == 0:
        return np.zeros((pad,) + a.shape[1:], a.dtype)
    reps = np.concatenate([a] * (pad // len(a) + 1))[:pad]
    return np.concatenate([a, reps])


def pad_rows_zero(a, target: int):
    """Zero-pad the leading (batch) axis up to ``target`` rows. For
    row-independent inference paths (``output()``) padded rows are dead
    compute sliced off by ``unpad``; stays on device for jax arrays."""
    if a is None:
        return None
    n = a.shape[0]
    if n >= target:
        return a
    import jax
    import jax.numpy as jnp

    pad_cfg = [(0, target - n)] + [(0, 0)] * (a.ndim - 1)
    if isinstance(a, jax.Array):
        return jnp.pad(a, pad_cfg)
    return np.pad(np.asarray(a), pad_cfg)


def unpad(out, n: int):
    """Slice a padded result (array or pytree of arrays) back to ``n`` rows."""
    import jax

    return jax.tree_util.tree_map(lambda o: o[:n], out)


def padded_label_mask(y, lm, n: int, scale: Optional[float] = None,
                      force: bool = False):
    """Label mask zero-weighting padded rows [n:] so the jitted step's loss
    averages over the n REAL examples only (exact equivalence with the
    unpadded fit).

    ``average_score`` keeps reference parity for per-example masks (divide by
    the full minibatch size B, BaseOutputLayer.computeScore semantics), so a
    0/1 validity mask alone would yield sum_real/B_pad instead of sum_real/n.
    The validity mask is therefore PRE-SCALED by B_pad/n: the per-example
    branch then gives sum(scores*mask)*(B_pad/n)/B_pad = sum_real/n exactly,
    and the rank-3 sum/sum(mask) branch is scale-invariant so it stays exact.

    Mask shape follows the label rank's masking convention: a user mask is
    multiplied by the scaled row validity; absent one, rank-2/3 labels get a
    per-example [B] weight (a [B,T] mask would flip average_score into its
    per-timestep sum/sum(mask) branch and rescale gradients by 1/T), and
    rank-4 (CnnLossLayer) labels get the per-pixel [B,H,W] mask its score()
    flattens (the flattened denominator B_pad*H*W needs the same B_pad/n
    correction).

    ``force=True`` materializes the (all-ones) mask even for an unpadded
    batch — the shape-bucketed fit path uses ONE calling convention for full
    and padded batches so they share a single compiled executable."""
    y = np.asarray(y)
    total = len(y)
    if scale is None and total == n and lm is None and not force:
        return lm
    valid = np.zeros(total, np.float32)
    valid[:n] = float(total) / float(n) if scale is None else float(scale)
    if lm is not None:
        lm = np.asarray(lm, np.float32)
        return lm * valid.reshape([total] + [1] * (lm.ndim - 1))
    if y.ndim == 4:
        return np.broadcast_to(valid[:, None, None], y.shape[:3]).copy()
    return valid


def pad_fit_batch(x, y, fm, lm, target: int, site: str = "fit"):
    """Pad a training batch's leading axis up to ``target`` rows, emitting
    the validity channels the loss/BatchNorm paths honor.

    Returns ``(x, y, fm, lm, ew)``: rows [n:] are tiled copies of real rows,
    ``ew`` is the per-example 0/1 weight (BatchNorm batch statistics exclude
    zero-weighted rows), and ``lm`` is the pre-scaled validity label mask
    (see ``padded_label_mask``) so the loss equals the mean over the n real
    rows. Called with ``len(x) == target`` it only materializes the all-ones
    channels, keeping ONE calling convention — and therefore one compiled
    executable — for full and partial batches alike."""
    n = len(x)
    if n > target:
        raise ValueError(f"batch of {n} rows exceeds pad target {target}")
    pad = target - n
    telemetry().record_hit(site, n, target)
    x, y, fm = (tile_pad(a, pad) if pad and a is not None else a
                for a in (x, y, fm))
    if pad and lm is not None:
        lm = tile_pad(lm, pad)
    lm = padded_label_mask(y, lm, n, force=True) if y is not None else lm
    ew = np.zeros(target, np.float32)
    ew[:n] = 1.0
    return x, y, fm, lm, ew


def pad_fit_multi(f, l, fm, lm, target: int, site: str = "fit"):
    """``pad_fit_batch`` for MultiDataSet tuples (ComputationGraph fit):
    every features/labels/masks member is row-padded, every output head gets
    its own pre-scaled validity label mask. Returns ``(f, l, fm, lm, ew)``."""
    n = len(f[0])
    if n > target:
        raise ValueError(f"batch of {n} rows exceeds pad target {target}")
    pad = target - n
    telemetry().record_hit(site, n, target)
    pad_t = lambda t: (tuple(tile_pad(a, pad) if a is not None else None
                             for a in t) if t is not None and pad else t)
    f, l, fm, lm = pad_t(f), pad_t(l), pad_t(fm), pad_t(lm)
    if l is not None:
        lms = lm if lm is not None else (None,) * len(l)
        lm = tuple(
            padded_label_mask(yi, lmi, n, force=True) if yi is not None else lmi
            for yi, lmi in zip(l, lms)
        )
        if all(m is None for m in lm):
            lm = None
    ew = np.zeros(target, np.float32)
    ew[:n] = 1.0
    return f, l, fm, lm, ew


def pad_time(x, target: int, fmask=None, axis: int = 1):
    """Pad the time axis of a [B, T, ...] sequence batch up to ``target``
    steps and return ``(x, fmask)`` where the mask zeroes the padded steps
    (synthesized as ones over the real steps when absent) so mask-honoring
    RNN/attention layers ignore them. Optional companion to batch bucketing
    for variable-length sequence serving."""
    x = np.asarray(x)
    t = x.shape[axis]
    if t >= target:
        if fmask is not None:
            fmask = np.asarray(fmask, np.float32)
        return x, fmask
    pad = target - t
    cfg = [(0, 0)] * x.ndim
    cfg[axis] = (0, pad)
    xp = np.pad(x, cfg)
    if fmask is None:
        fmask = np.ones((x.shape[0], t), np.float32)
    else:
        fmask = np.asarray(fmask, np.float32)
    fmask = np.pad(fmask, [(0, 0), (0, pad)])
    return xp, fmask
