"""Persistent XLA compilation cache — first-epoch compile amortization.

Big unrolled programs (the Word2Vec epoch scan: 52.2s of compiles on the
first epoch, ~5x a warm epoch — BENCH_r04 end_to_end_split_sec; the
transformer/flash kernels: 20-40s each) recompile from scratch in every
fresh process. JAX ships a persistent on-disk cache that keys compiled
executables by HLO fingerprint; enabling it makes the SECOND process's
first epoch warm.

Opt-in (global config mutation should never happen on library import):

    from deeplearning4j_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()            # ~/.cache/deeplearning4j_tpu/xla

or set ``DL4J_TPU_COMPILE_CACHE=/path`` (empty value = the default dir)
and call ``enable_compilation_cache_from_env()`` — bench.py does this so
driver re-runs skip the Word2Vec scan compile.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache",
                        "deeplearning4j_tpu", "xla")


def enable_compilation_cache(cache_dir: Optional[str] = None,
                             min_compile_time_secs: float = 1.0) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` (created
    if missing). Only compiles slower than ``min_compile_time_secs`` are
    persisted — the long-pole scans/kernels, not trivial jits."""
    import jax

    path = cache_dir or _DEFAULT
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    except AttributeError:  # older jax: flag absent; cache still works
        pass
    return path


def enable_compilation_cache_from_env() -> Optional[str]:
    """Enable the cache iff DL4J_TPU_COMPILE_CACHE is set (empty value =
    default location). Returns the directory or None."""
    val = os.environ.get("DL4J_TPU_COMPILE_CACHE")
    if val is None:
        return None
    return enable_compilation_cache(val or None)
