"""DL4J model-zip interop: import/export of the reference's saved-model format.

Reference format (util/ModelSerializer.java:110-150): a zip with
  configuration.json   MultiLayerConfiguration.toJson() (jackson, layer
                       subtypes as WRAPPER_OBJECT names — conf/layers/Layer.java:53-85)
  coefficients.bin     Nd4j.write(model.params(), dos): shapeInfo int buffer
                       then the data buffer, both in the ND4J DataBuffer
                       stream format (allocation-mode UTF8 string, int32
                       length, dtype UTF8 string, big-endian payload)
  updaterState.bin     optional, same binary layout.

Param-vector layout per layer (the flat view intervals in nn/params/*.java):
  dense/output/embedding  [ W: F-order (nIn,nOut) | b ]        (DefaultParamInitializer.java:116-139)
  convolution             [ b | W: C-order (nOut,nIn,kh,kw) ]  (ConvolutionParamInitializer.java:118-153)
  batchNormalization      [ gamma | beta | mean | var ]        (BatchNormalizationParamInitializer.java:79-114)
  gravesLSTM / LSTM       [ Wx: F (nIn,4H) | RW: F (H,4H[+3]) | b(4H) ]
                          DL4J gate blocks are [g,f,o,i] — block 0 is the
                          tanh candidate ("inputActivations"), block 3 the
                          sigmoid input gate ("inputModGate") — with peephole
                          columns [wFF,wOO,wGG] = [f(prev c), o(cur c),
                          i(prev c)] (LSTMHelpers.java:71,205-320,
                          GravesLSTMParamInitializer.java:117-160)
  simpleRnn               [ W: F (nIn,nOut) | RW: F (nOut,nOut) | b ]

Layout conversions to this framework's TPU-native conventions:
  conv W    (nOut,nIn,kh,kw) C-order  ->  (kh,kw,nIn,nOut) NHWC kernels
  dense-after-conv W rows: DL4J flattens NCHW (c,h,w); we flatten NHWC
            (h,w,c) — rows are permuted accordingly
  LSTM      DL4J blocks [g,f,o,i] -> ours [i,f,g,o]; peepholes
            [wGG,wFF,wOO] -> [p_i,p_f,p_o]
  BN        mean/var move to the (non-trainable) state pytree.

The fixtures committed under tests/fixtures/ are produced by
``export_dl4j_zip`` below — this environment has no JVM/ND4J to emit true
reference bytes, so the binary layout is implemented from the reference
sources cited above and the fixture proves reader/writer agreement plus the
cross-layout (NCHW->NHWC, F-order, gate-order) parameter mapping against an
independent NumPy NCHW forward pass (tests/test_dl4j_import.py).
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.input_type import InputType

# ---------------------------------------------------------------------------
# ND4J binary array serde
# ---------------------------------------------------------------------------

_DTYPES = {"FLOAT": ("f", 4, np.float32), "DOUBLE": ("d", 8, np.float64),
           "INT": ("i", 4, np.int32), "LONG": ("q", 8, np.int64)}


def _read_utf(s: io.BufferedIOBase) -> str:
    """Java DataOutputStream.writeUTF: u16 byte-length + modified-UTF8."""
    (n,) = struct.unpack(">H", s.read(2))
    return s.read(n).decode("utf-8")


def _write_utf(s: io.BufferedIOBase, text: str):
    b = text.encode("utf-8")
    s.write(struct.pack(">H", len(b)))
    s.write(b)


def read_databuffer(s: io.BufferedIOBase) -> np.ndarray:
    """One ND4J DataBuffer: allocation-mode UTF, int32 length, dtype UTF,
    then big-endian elements (BaseDataBuffer.write)."""
    _alloc = _read_utf(s)
    (length,) = struct.unpack(">i", s.read(4))
    dtype = _read_utf(s)
    if dtype not in _DTYPES:
        raise ValueError(f"Unsupported ND4J dtype {dtype!r}")
    _, size, np_dt = _DTYPES[dtype]
    raw = s.read(length * size)
    if len(raw) != length * size:
        raise ValueError("Truncated ND4J data buffer")
    return np.frombuffer(raw, dtype=np.dtype(np_dt).newbyteorder(">"),
                         count=length).astype(np_dt)


def write_databuffer(s: io.BufferedIOBase, arr: np.ndarray, dtype: str):
    _, size, np_dt = _DTYPES[dtype]
    flat = np.ascontiguousarray(arr, dtype=np_dt).ravel()
    _write_utf(s, "DIRECT")
    s.write(struct.pack(">i", flat.size))
    _write_utf(s, dtype)
    s.write(flat.astype(np.dtype(np_dt).newbyteorder(">")).tobytes())


def read_nd4j(s: io.BufferedIOBase) -> np.ndarray:
    """Nd4j.read: shapeInfo int buffer [rank, shape.., stride.., offset,
    elementWiseStride, order-char] followed by the data buffer.

    Obligations per docs/DL4J_DIALECT.md: the STRIDES are the layout ground
    truth (the order char is only the fallback for ambiguous shapes),
    nonzero offsets are rejected loudly, and the shapeInfo length must be
    2*rank + 4."""
    shape_info = read_databuffer(s)
    rank = int(shape_info[0])
    if len(shape_info) != 2 * rank + 4:
        raise ValueError(
            f"shapeInfo length {len(shape_info)} != 2*rank+4 (rank {rank})")
    shape = tuple(int(d) for d in shape_info[1:1 + rank])
    strides = tuple(int(d) for d in shape_info[1 + rank:1 + 2 * rank])
    offset = int(shape_info[1 + 2 * rank])
    if offset != 0:
        raise ValueError(f"nonzero ND4J array offset {offset} unsupported")
    order = chr(int(shape_info[2 * rank + 3]))

    def contiguous(o):
        acc, out = 1, [0] * rank
        for i in (range(rank - 1, -1, -1) if o == "c" else range(rank)):
            out[i] = acc
            acc *= shape[i]
        return tuple(out)

    if strides == contiguous("c"):
        order = "c"          # strides win over a disagreeing order char
    elif strides == contiguous("f"):
        order = "f"
    else:
        raise ValueError(
            f"non-contiguous ND4J strides {strides} for shape {shape}")
    data = read_nd4j_databuffer_data(s)
    if data.size != int(np.prod(shape)):
        raise ValueError(f"data length {data.size} != prod{shape}")
    return np.reshape(data, shape, order=order)


def read_nd4j_databuffer_data(s) -> np.ndarray:
    return read_databuffer(s)


def write_nd4j(s: io.BufferedIOBase, arr: np.ndarray, dtype: str = "FLOAT"):
    arr = np.asarray(arr)
    if arr.ndim == 1:
        arr = arr[None, :]  # DL4J params() is a [1,N] row vector
    rank = arr.ndim
    c = np.ascontiguousarray(arr)
    strides = []
    acc = 1
    for d in reversed(c.shape):
        strides.insert(0, acc)
        acc *= d
    info = [rank, *c.shape, *strides, 0, 1, ord("c")]
    write_databuffer(s, np.asarray(info, np.int32), "INT")
    write_databuffer(s, c, dtype)


# ---------------------------------------------------------------------------
# JSON <-> layer-config conversion
# ---------------------------------------------------------------------------

_ACT_MAP = {
    # DL4J activation name (lowercased, 'activation' stripped) -> the name
    # REGISTERED in nn/activations.py
    "relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh", "softmax": "softmax",
    "identity": "identity", "lrelu": "leakyrelu", "leakyrelu": "leakyrelu",
    "elu": "elu", "softplus": "softplus", "softsign": "softsign",
    "hardtanh": "hardtanh", "hardsigmoid": "hardsigmoid", "cube": "cube",
    "rationaltanh": "rationaltanh", "rectifiedtanh": "rectifiedtanh",
    "selu": "selu", "swish": "swish", "gelu": "gelu", "mish": "mish",
    "relu6": "relu6", "thresholdedrelu": "thresholdedrelu",
    "logsoftmax": "logsoftmax",
}

_LOSS_MAP = {
    "mcxent": "mcxent", "negativeloglikelihood": "mcxent", "mse": "mse",
    "xent": "xent", "l1": "l1", "l2": "l2", "squaredloss": "mse",
    "cosineproximity": "cosine_proximity", "hinge": "hinge",
    "squaredhinge": "squared_hinge", "kldivergence": "kld", "poisson": "poisson",
    "meanabsoluteerror": "mae", "meansquaredlogarithmicerror": "msle",
    "meanabsolutepercentageerror": "mape",
}

_WEIGHT_INIT_MAP = {
    # DL4J WeightInit enum (lowercased) -> the name registered in
    # nn/initializers.py
    "xavier": "xavier", "xavier_uniform": "xavier_uniform", "xavieruniform": "xavier_uniform",
    "xavierlegacy": "xavier", "xavierfanin": "xavier_fan_in", "relu": "relu",
    "reluuniform": "relu_uniform", "uniform": "uniform", "zero": "zero",
    "ones": "ones", "normal": "normal", "lecunnormal": "lecun_normal",
    "lecununiform": "lecun_uniform", "distribution": "normal",
    "identity": "identity",
    "varscalingnormalfanin": "varscaling_normal_fan_in",
    "varscalingnormalfanout": "varscaling_normal_fan_out",
    "varscalingnormalfanavg": "varscaling_normal_fan_avg",
    "sigmoiduniform": "sigmoid_uniform",
}


def _parse_activation(d: Dict[str, Any]) -> str:
    """Accept 'activationFn': {'ReLU': {}} (typed), a plain string, or the
    pre-0.7 'activationFunction': 'relu'."""
    fn = d.get("activationFn")
    if fn is None:
        fn = d.get("activationFunction")
    if fn is None:
        return "identity"
    if isinstance(fn, str):
        key = fn.lower().replace("activation", "")
    elif isinstance(fn, dict):
        if "@class" in fn:
            key = fn["@class"].rsplit(".", 1)[-1].lower().replace("activation", "")
        else:
            key = next(iter(fn)).lower().replace("activation", "")
    else:
        raise ValueError(f"Unparseable activation {fn!r}")
    if key not in _ACT_MAP:
        raise ValueError(f"Unsupported DL4J activation {fn!r}")
    return _ACT_MAP[key]


def _parse_loss(d: Dict[str, Any]) -> str:
    fn = d.get("lossFn")
    if fn is None:
        fn = d.get("lossFunction")
    if fn is None:
        return "mcxent"
    if isinstance(fn, str):
        key = fn.lower()
    elif isinstance(fn, dict):
        if "@class" in fn:
            key = fn["@class"].rsplit(".", 1)[-1].lower()
        else:
            key = next(iter(fn)).lower()
    else:
        raise ValueError(f"Unparseable loss {fn!r}")
    key = key.replace("loss", "", 1) if key.startswith("loss") else key
    if key not in _LOSS_MAP:
        raise ValueError(f"Unsupported DL4J loss {fn!r}")
    return _LOSS_MAP[key]


def _parse_weight_init(d: Dict[str, Any]) -> str:
    wi = d.get("weightInit")
    if wi is None:
        return "xavier"
    key = str(wi).lower()
    return _WEIGHT_INIT_MAP.get(key, "xavier")


def _parse_updater(d: Dict[str, Any]) -> Optional[dict]:
    """Layer 'iUpdater' typed object ({'Adam': {...}}) or legacy
    'updater': 'ADAM' + 'learningRate' fields."""
    iu = d.get("iUpdater") or d.get("iupdater")
    if isinstance(iu, dict):
        if "@class" in iu:
            name = iu["@class"].rsplit(".", 1)[-1].lower()
            body = {k: v for k, v in iu.items() if k != "@class"}
        else:
            name = next(iter(iu)).lower()
            body = iu[name] if name in iu else next(iter(iu.values()))
        name = name.replace("updater", "")
        spec = {"type": {"nesterovs": "nesterovs", "sgd": "sgd", "adam": "adam",
                         "adamax": "adamax", "nadam": "nadam", "amsgrad": "amsgrad",
                         "adagrad": "adagrad", "adadelta": "adadelta",
                         "rmsprop": "rmsprop", "noop": "noop"}.get(name, "sgd")}
        lr = body.get("learningRate")
        if lr is not None:
            spec["lr"] = float(lr)
        for src, dst in (("beta1", "beta1"), ("beta2", "beta2"), ("epsilon", "eps"),
                         ("momentum", "momentum"), ("rmsDecay", "decay"), ("rho", "rho")):
            if src in body:
                spec[dst] = float(body[src])
        return spec
    upd = d.get("updater")
    if isinstance(upd, str):
        spec = {"type": upd.lower()}
        if "learningRate" in d:
            spec["lr"] = float(d["learningRate"])
        return spec
    return None


def _common_kwargs(d: Dict[str, Any]) -> dict:
    kw = {}
    if d.get("layerName"):
        kw["name"] = d["layerName"]
    drop = d.get("dropOut", 0.0) or 0.0
    if 0.0 < drop < 1.0:
        # DL4J dropOut is the RETAIN probability; ours is the drop rate
        kw["dropout"] = 1.0 - float(drop)
    for field, ours in (("l1", "l1"), ("l2", "l2")):
        v = d.get(field, 0.0) or 0.0
        if v:
            kw[ours] = float(v)
    return kw


def _conv_mode(d: Dict[str, Any]) -> str:
    return str(d.get("convolutionMode") or "Truncate").lower()


def dl4j_layer_to_config(type_name: str, d: Dict[str, Any]):
    """One DL4J layer JSON object -> (our LayerConfig, dl4j_dict)."""
    from deeplearning4j_tpu.nn import layers as L

    act = _parse_activation(d)
    wi = _parse_weight_init(d)
    kw = _common_kwargs(d)
    # per-layer iUpdater override is first-class in DL4J; carry it onto the
    # layer config so model._build_updaters honors it (and updater-state
    # accumulators land in matching opt_state structures)
    lupd = _parse_updater(d)
    if lupd is not None:
        kw["updater"] = lupd
    n_in = int(d.get("nin") or d.get("nIn") or 0) or None
    n_out = int(d.get("nout") or d.get("nOut") or 0) or None
    t = type_name

    if t == "dense":
        return L.Dense(n_in=n_in, n_out=n_out, activation=act, weight_init=wi,
                       has_bias=bool(d.get("hasBias", True)), **kw)
    if t == "output":
        return L.OutputLayer(n_in=n_in, n_out=n_out, activation=act,
                             loss=_parse_loss(d), weight_init=wi,
                             has_bias=bool(d.get("hasBias", True)), **kw)
    if t == "rnnoutput":
        return L.RnnOutputLayer(n_in=n_in, n_out=n_out, activation=act,
                                loss=_parse_loss(d), weight_init=wi,
                                has_bias=bool(d.get("hasBias", True)), **kw)
    if t == "loss":
        return L.LossLayer(activation=act, loss=_parse_loss(d))
    if t == "convolution":
        return L.Conv2D(n_in=n_in, n_out=n_out, activation=act, weight_init=wi,
                        kernel=tuple(d["kernelSize"]), stride=tuple(d.get("stride", (1, 1))),
                        padding=tuple(d.get("padding", (0, 0))),
                        convolution_mode=_conv_mode(d),
                        has_bias=bool(d.get("hasBias", True)), **kw)
    if t == "subsampling":
        pool = str(d.get("poolingType", "MAX")).lower()
        return L.Subsampling2D(kernel=tuple(d["kernelSize"]),
                               stride=tuple(d.get("stride", (2, 2))),
                               padding=tuple(d.get("padding", (0, 0))),
                               convolution_mode=_conv_mode(d), pooling=pool)
    if t == "batchNormalization":
        return L.BatchNorm(decay=float(d.get("decay", 0.9)),
                           eps=float(d.get("eps", 1e-5)),
                           use_gamma_beta=not bool(d.get("lockGammaBeta", False)),
                           updater=kw.get("updater"))
    if t == "localResponseNormalization":
        return L.LocalResponseNormalization(
            k=float(d.get("k", 2.0)), n=int(d.get("n", 5)),
            alpha=float(d.get("alpha", 1e-4)), beta=float(d.get("beta", 0.75)))
    if t in ("gravesLSTM", "LSTM"):
        cls = L.GravesLSTM if t == "gravesLSTM" else L.LSTM
        return cls(n_in=n_in, n_out=n_out, activation=act, weight_init=wi,
                   gate_activation=_ACT_MAP.get(
                       str(d.get("gateActivationFn", "sigmoid")).lower(), "sigmoid")
                   if isinstance(d.get("gateActivationFn"), str) else "sigmoid",
                   forget_gate_bias_init=float(d.get("forgetGateBiasInit", 1.0)), **kw)
    if t == "SimpleRnn":
        return L.SimpleRnn(n_in=n_in, n_out=n_out, activation=act, weight_init=wi, **kw)
    if t == "embedding":
        return L.Embedding(n_in=n_in, n_out=n_out, weight_init=wi,
                           has_bias=bool(d.get("hasBias", True)),
                           updater=kw.get("updater"))
    if t == "activation":
        return L.ActivationLayer(activation=act)
    if t == "dropout":
        return L.DropoutLayer(dropout=kw.get("dropout", 0.5))
    if t == "GlobalPooling":
        return L.GlobalPooling(pooling=str(d.get("poolingType", "MAX")).lower())
    raise ValueError(f"DL4J layer type {type_name!r} not supported by the importer")


# ---------------------------------------------------------------------------
# Parameter mapping
# ---------------------------------------------------------------------------

def _take(flat: np.ndarray, pos: int, n: int) -> Tuple[np.ndarray, int]:
    if pos + n > flat.size:
        # shared by coefficients.bin AND updaterState.bin consumption
        raise ValueError(
            f"binary parameter stream exhausted: need {pos + n}, have {flat.size}")
    return flat[pos:pos + n], pos + n


def _lstm_block_perm(H: int) -> List[Tuple[int, int]]:
    """(our_block, dl4j_block) pairs: ours [i,f,g,o] <- DL4J [g,f,o,i]."""
    return [(0, 3), (1, 1), (2, 0), (3, 2)]


def _map_layer_params(cfg, d: Dict[str, Any], flat: np.ndarray, pos: int,
                      in_type: InputType) -> Tuple[dict, dict, int]:
    """Consume one layer's segment. Returns (params, state, new_pos) in OUR
    conventions."""
    from deeplearning4j_tpu.nn import layers as L

    name = type(cfg).__name__
    if isinstance(cfg, (L.Conv2D,)) and not isinstance(cfg, (L.Deconv2D,)):
        n_out = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.channels
        kh, kw = cfg.kernel
        params = {}
        if cfg.has_bias:
            b, pos = _take(flat, pos, n_out)
            params["b"] = b.astype(np.float32)
        w, pos = _take(flat, pos, n_out * n_in * kh * kw)
        w = w.reshape(n_out, n_in, kh, kw)            # C order
        params["W"] = np.transpose(w, (2, 3, 1, 0)).astype(np.float32)  # -> (kh,kw,in,out)
        return params, {}, pos

    if isinstance(cfg, (L.GravesLSTM, L.LSTM)):
        H = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.size
        graves = isinstance(cfg, L.GravesLSTM)
        wx, pos = _take(flat, pos, n_in * 4 * H)
        wx = wx.reshape(n_in, 4 * H, order="F")
        rw_cols = 4 * H + (3 if graves else 0)
        rw, pos = _take(flat, pos, H * rw_cols)
        rw = rw.reshape(H, rw_cols, order="F")
        b, pos = _take(flat, pos, 4 * H)
        Wx = np.empty_like(wx)
        Wh = np.empty((H, 4 * H), wx.dtype)
        bb = np.empty_like(b)
        for ours, theirs in _lstm_block_perm(H):
            Wx[:, ours * H:(ours + 1) * H] = wx[:, theirs * H:(theirs + 1) * H]
            Wh[:, ours * H:(ours + 1) * H] = rw[:, theirs * H:(theirs + 1) * H]
            bb[ours * H:(ours + 1) * H] = b[theirs * H:(theirs + 1) * H]
        params = {"Wx": Wx.astype(np.float32), "Wh": Wh.astype(np.float32),
                  "b": bb.astype(np.float32)}
        if graves:
            # DL4J peephole cols [wFF, wOO, wGG] -> ours [p_i, p_f, p_o]
            wff, woo, wgg = rw[:, 4 * H], rw[:, 4 * H + 1], rw[:, 4 * H + 2]
            params["peephole"] = np.concatenate([wgg, wff, woo]).astype(np.float32)
        return params, {}, pos

    if isinstance(cfg, L.SimpleRnn):
        H = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.size
        w, pos = _take(flat, pos, n_in * H)
        rw, pos = _take(flat, pos, H * H)
        b, pos = _take(flat, pos, H)
        return ({"Wx": w.reshape(n_in, H, order="F").astype(np.float32),
                 "Wh": rw.reshape(H, H, order="F").astype(np.float32),
                 "b": b.astype(np.float32)}, {}, pos)

    if isinstance(cfg, L.BatchNorm):
        n = in_type.channels if in_type.kind == "conv" else in_type.flat_size()
        params = {}
        if cfg.use_gamma_beta:
            g, pos = _take(flat, pos, n)
            bta, pos = _take(flat, pos, n)
            params = {"gamma": g.astype(np.float32), "beta": bta.astype(np.float32)}
        mean, pos = _take(flat, pos, n)
        var, pos = _take(flat, pos, n)
        return params, {"mean": mean.astype(np.float32), "var": var.astype(np.float32)}, pos

    if name in ("Dense", "OutputLayer", "RnnOutputLayer", "Embedding"):
        n_out = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.flat_size()
        w, pos = _take(flat, pos, n_in * n_out)
        W = w.reshape(n_in, n_out, order="F").astype(np.float32)
        if in_type.kind == "conv":
            # DL4J flattened (c,h,w); our preprocessor flattens (h,w,c)
            H_, W_, C_ = in_type.height, in_type.width, in_type.channels
            perm = np.arange(n_in).reshape(C_, H_, W_).transpose(1, 2, 0).ravel()
            W = W[perm]
        params = {"W": W}
        if getattr(cfg, "has_bias", True):
            b, pos = _take(flat, pos, n_out)
            params["b"] = b.astype(np.float32)
        return params, {}, pos

    # param-free layers (subsampling, activation, dropout, lrn, pooling, loss)
    return {}, {}, pos


# ---------------------------------------------------------------------------
# Updater state (updaterState.bin)
# ---------------------------------------------------------------------------
# The reference flattens optimizer state per UPDATER BLOCK: contiguous
# (layer, variable) pairs with equal updater configs merge into one block
# (BaseMultiLayerUpdater.java:56-127, UpdaterUtils.updaterConfigurationsEquals),
# and each block's view is [acc1(all vars) | acc2(all vars) | ...] — the
# ND4J GradientUpdater.setStateViewArray split (AdamUpdater: m then v;
# AdaDeltaUpdater: msg then msdx; AMSGradUpdater: m, v, vHat). Layers walk in
# the same order as the param flattening (MultiLayerUpdater: network layers;
# ComputationGraphUpdater.getOrderedLayers: topological order); variables walk
# in paramTable order = the per-layer flat layout above. BatchNorm mean/var
# use NoOp (BatchNormalization.java:144-155) — zero state, but they BREAK
# block contiguity.

# our opt_state dict keys, in the order ND4J splits the block state view
_STATE_KEYS = {
    "sgd": [], "noop": [],
    "nesterovs": ["v"],          # NesterovsUpdater: v (momentum)
    "adagrad": ["h"],            # AdaGradUpdater: historicalGradient
    "rmsprop": ["c"],            # RmsPropUpdater: lastGradient
    "adadelta": ["eg", "edx"],   # AdaDeltaUpdater: msg, msdx
    "adam": ["m", "v"], "nadam": ["m", "v"],
    "adamax": ["m", "v"],        # AdaMaxUpdater: m, u
    "amsgrad": ["m", "v", "vmax"],  # AMSGradUpdater: m, v, vHat
}


def _dl4j_var_sizes(cfg, in_type: InputType) -> List[Tuple[str, int]]:
    """Per-variable (kind, size) in DL4J paramTable order — MUST mirror the
    consumption order of ``_map_layer_params``. kind: 'train' uses the
    layer's updater; 'stats' is BN mean/var (NoOp)."""
    from deeplearning4j_tpu.nn import layers as L

    name = type(cfg).__name__
    if isinstance(cfg, L.Conv2D) and not isinstance(cfg, L.Deconv2D):
        n_in = cfg.n_in if cfg.n_in else in_type.channels
        kh, kw = cfg.kernel
        out = [("train", cfg.n_out)] if cfg.has_bias else []
        return out + [("train", cfg.n_out * n_in * kh * kw)]
    if isinstance(cfg, (L.GravesLSTM, L.LSTM)):
        H = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.size
        rw = H * (4 * H + (3 if isinstance(cfg, L.GravesLSTM) else 0))
        return [("train", n_in * 4 * H), ("train", rw), ("train", 4 * H)]
    if isinstance(cfg, L.SimpleRnn):
        H = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.size
        return [("train", n_in * H), ("train", H * H), ("train", H)]
    if isinstance(cfg, L.BatchNorm):
        n = in_type.channels if in_type.kind == "conv" else in_type.flat_size()
        out = [("train", n), ("train", n)] if cfg.use_gamma_beta else []
        return out + [("stats", n), ("stats", n)]
    if name in ("Dense", "OutputLayer", "RnnOutputLayer", "Embedding"):
        n_out = cfg.n_out
        n_in = cfg.n_in if cfg.n_in else in_type.flat_size()
        out = [("train", n_in * n_out)]
        if getattr(cfg, "has_bias", True):
            out.append(("train", n_out))
        return out
    return []


def _spec_state_keys(spec: Optional[dict]) -> List[str]:
    t = (spec or {}).get("type", "sgd")
    if t not in _STATE_KEYS:
        raise ValueError(f"unknown updater type {t!r} in updater-state mapping")
    return _STATE_KEYS[t]


def _canon_spec(spec: Optional[dict]) -> dict:
    """Normalize an updater spec (fill defaults, drop non-identity fields) so
    block-equality compares like DL4J's IUpdater.equals — a layer whose JSON
    omits a default field must still merge with its neighbors."""
    from deeplearning4j_tpu.train.updaters import normalize_updater

    out = dict(normalize_updater(spec if spec else {"type": "sgd"}))
    out.pop("schedule", None)
    return out


def _updater_var_blocks(layer_entries, spec_for_entry):
    """Shared import/export block segmentation. ``layer_entries``: ordered
    [(cfg, in_type)]-like; ``spec_for_entry(li)`` -> that layer's canonical
    trainable-var updater spec. Returns (var_recs, blocks) where var_recs =
    [(li, vi, size, spec_json, spec)] and blocks groups contiguous equal
    spec_json runs, mirroring BaseMultiLayerUpdater.java:56-127."""
    var_recs = []
    noop_json = json.dumps(_canon_spec({"type": "noop"}), sort_keys=True)
    for li, (cfg, in_type) in enumerate(layer_entries):
        spec = spec_for_entry(li)
        spec_json = json.dumps(spec, sort_keys=True)
        for vi, (kind, size) in enumerate(_dl4j_var_sizes(cfg, in_type)):
            if kind == "stats":
                var_recs.append((li, vi, size, noop_json, {"type": "noop"}))
            else:
                var_recs.append((li, vi, size, spec_json, spec))
    blocks: List[Tuple[dict, list]] = []
    for rec in var_recs:
        if blocks and blocks[-1][1][-1][3] == rec[3]:
            blocks[-1][1].append(rec)
        else:
            blocks.append((rec[4], [rec]))
    return var_recs, blocks


def _consume_updater_state(layer_entries, flat: np.ndarray, global_spec: dict):
    """layer_entries: ordered [(cfg, layer_json_dict, in_type)]. Returns
    {layer_pos: {acc_key: params-shaped-dict}} with every accumulator mapped
    through the same layout conversions as the weights (an Adam ``m`` for a
    conv W permutes (out,in,kh,kw)->(kh,kw,in,out) exactly like W itself)."""
    gspec = _canon_spec(global_spec)

    def spec_for(li):
        lspec = _parse_updater(layer_entries[li][1])
        return _canon_spec(lspec) if lspec else gspec

    _, blocks = _updater_var_blocks(
        [(cfg, it) for cfg, _d, it in layer_entries], spec_for)

    pos = 0
    segs: Dict[Tuple[int, int, str], np.ndarray] = {}
    for spec, recs in blocks:
        for key in _spec_state_keys(spec):
            for li, vi, size, _, _ in recs:
                seg, pos = _take(flat, pos, size)
                segs[(li, vi, key)] = seg
    if pos != flat.size:
        raise ValueError(
            f"updaterState.bin has {flat.size} values but the configuration's "
            f"updater blocks consume {pos} — block layout mismatch")

    out: Dict[int, Dict[str, dict]] = {}
    for li, (cfg, d, in_type) in enumerate(layer_entries):
        sizes = _dl4j_var_sizes(cfg, in_type)
        keys = {k for (l2, _, k) in segs if l2 == li}
        for key in sorted(keys):
            pieces = [segs.get((li, vi, key), np.zeros(size, np.float32))
                      for vi, (_, size) in enumerate(sizes)]
            fake = np.concatenate(pieces) if pieces else np.zeros(0, np.float32)
            p, _st, _ = _map_layer_params(cfg, d, fake, 0, in_type)
            out.setdefault(li, {})[key] = p
    return out


def _merge_opt_state(existing, accs: Dict[str, dict]):
    """Overlay imported accumulators onto a layer's initialized opt_state,
    keeping dtype (mixed-precision keeps f32 accumulators)."""
    import jax.numpy as jnp

    if not isinstance(existing, dict):
        return existing
    new = dict(existing)
    for key, tree in accs.items():
        if key not in new:
            continue
        cur = new[key]
        new[key] = {k: jnp.asarray(v, dtype=np.asarray(cur[k]).dtype
                                   if isinstance(cur, dict) and k in cur
                                   else np.float32)
                    for k, v in tree.items()}
    return new


def _updater_to_dl4j_json(spec: dict) -> dict:
    """Our updater spec -> DL4J iUpdater WRAPPER_OBJECT JSON (inverse of
    ``_parse_updater``)."""
    names = {"sgd": "Sgd", "nesterovs": "Nesterovs", "adam": "Adam",
             "adamax": "AdaMax", "nadam": "Nadam", "amsgrad": "AMSGrad",
             "adagrad": "AdaGrad", "adadelta": "AdaDelta",
             "rmsprop": "RmsProp", "noop": "NoOp"}
    body: Dict[str, Any] = {}
    if "lr" in spec:
        body["learningRate"] = spec["lr"]
    for ours, theirs in (("beta1", "beta1"), ("beta2", "beta2"),
                         ("eps", "epsilon"), ("momentum", "momentum"),
                         ("decay", "rmsDecay"), ("rho", "rho")):
        if ours in spec:
            body[theirs] = spec[ours]
    return {names.get(spec.get("type", "sgd"), "Sgd"): body}


# ---------------------------------------------------------------------------
# Import
# ---------------------------------------------------------------------------

def _infer_input_type(layer_dicts, preprocs: Dict[str, Any],
                      input_type: Optional[InputType]) -> InputType:
    if input_type is not None:
        return input_type
    pp0 = (preprocs or {}).get("0")
    if isinstance(pp0, dict):
        body = next(iter(pp0.values())) if "@class" not in pp0 else pp0
        h = body.get("inputHeight") or body.get("numRows")
        w = body.get("inputWidth") or body.get("numColumns")
        c = body.get("numChannels")
        if h and w and c:
            return InputType.convolutional_flat(int(h), int(w), int(c))
    t0, d0 = layer_dicts[0]
    n_in = int(d0.get("nin") or d0.get("nIn") or 0)
    if t0 in ("gravesLSTM", "LSTM", "SimpleRnn", "rnnoutput"):
        return InputType.recurrent(n_in)
    if t0 == "convolution":
        raise ValueError(
            "Cannot infer the conv input height/width from a DL4J config with "
            "no input preprocessor — pass input_type=InputType.convolutional(h,w,c)")
    return InputType.feed_forward(n_in)


def import_dl4j_zip(path: str, input_type: Optional[InputType] = None):
    """Load a DL4J MultiLayerNetwork OR ComputationGraph zip -> our model
    with the parameters (and BN running stats) mapped into native layouts.

    CG weights: the reference splits the flat ``coefficients.bin`` view by
    walking vertices in the runtime topological order — Kahn's algorithm with
    a FIFO queue over vertex indices (inputs numbered first in networkInputs
    order, then config vertices in JSON/insertion order), seeded and expanded
    in ascending-index order (graph/ComputationGraph.java:377-470, 1211-1300;
    deterministic because Java HashMap/HashSet over small Integer keys
    iterate ascending). ``_dl4j_topo_order`` replicates exactly that walk.
    """
    from deeplearning4j_tpu.nn.model import MultiLayerConfiguration, MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        conf = json.loads(zf.read("configuration.json").decode("utf-8"))
        names = set(zf.namelist())
        coeff = zf.read("coefficients.bin") if "coefficients.bin" in names else b""
        updater_bin = (zf.read("updaterState.bin")
                       if "updaterState.bin" in names else b"")

    if "vertices" in conf and "confs" not in conf:
        parsed = _parse_cg_conf(conf)
        model = _import_dl4j_graph_conf(conf, input_type, parsed=parsed)
        if coeff:
            flat = read_nd4j(io.BytesIO(coeff)).ravel().astype(np.float32)
            uflat = (read_nd4j(io.BytesIO(updater_bin)).ravel().astype(np.float32)
                     if updater_bin else None)
            _map_cg_weights(model, parsed, flat, uflat)
            # iterationCount lives on each LayerVertex's NeuralNetConfiguration
            for _vn, (vt, body) in parsed[3].items():
                if vt == "LayerVertex":
                    it_count = (body.get("layerConf") or {}).get("iterationCount")
                    if it_count:
                        model.iteration = int(it_count)
                        break
            model.weights_imported = True
        else:
            model.weights_imported = False  # config-only zip: fresh init
        return model

    confs = conf.get("confs") or []
    if not confs:
        raise ValueError("configuration.json has no 'confs' — not a MultiLayerNetwork zip")
    if not coeff:
        raise ValueError(
            f"{path!r} has no coefficients.bin — a MultiLayerNetwork zip "
            "without weights cannot be imported (the reference always writes "
            "one, ModelSerializer.java:110-150)")
    layer_dicts: List[Tuple[str, dict]] = []
    for c in confs:
        layer = c.get("layer") or {}
        if not isinstance(layer, dict) or len(layer) != 1:
            raise ValueError(f"Unparseable layer entry: {layer!r}")
        t = next(iter(layer))
        layer_dicts.append((t, layer[t]))

    our_layers = tuple(dl4j_layer_to_config(t, d) for t, d in layer_dicts)
    updater = None
    for _, d in layer_dicts:
        updater = _parse_updater(d)
        if updater:
            break

    it = _infer_input_type(layer_dicts, conf.get("inputPreProcessors"), input_type)
    bpt = str(conf.get("backpropType", "Standard"))
    mlc = MultiLayerConfiguration(
        layers=our_layers,
        input_type=it,
        updater=updater or {"type": "sgd", "lr": 0.1},
        seed=int(confs[0].get("seed", 12345) or 12345),
        backprop_type="tbptt" if bpt.lower().startswith("truncated") else "standard",
        tbptt_fwd_length=int(conf.get("tbpttFwdLength", 20)),
        tbptt_back_length=int(conf.get("tbpttBackLength", 20)),
    )
    model = MultiLayerNetwork(mlc).init()

    flat = read_nd4j(io.BytesIO(coeff)).ravel().astype(np.float32)
    pos = 0
    new_params = list(model.params)
    new_state = list(model.state)
    li = 0  # index over original (non-preprocessor) layers
    entries = []          # (cfg, layer_json, in_type) in flatten order
    entry_model_idx = []  # model layer index per entry
    import jax.numpy as jnp

    for idx, lcfg in enumerate(model.layers):
        if type(lcfg).__module__.endswith("preprocessors"):
            continue
        cfg = lcfg
        in_type = model.layer_input_types[idx]
        # The flatten-order permutation for dense-after-conv needs the CONV
        # shape, which the auto-inserted CnnToFeedForward preprocessor hides:
        # use the preprocessor's input type when one precedes this layer.
        if idx > 0 and type(model.layers[idx - 1]).__module__.endswith("preprocessors"):
            pre_in = model.layer_input_types[idx - 1]
            if pre_in.kind == "conv":
                in_type = pre_in
        p, st, pos = _map_layer_params(cfg, layer_dicts[li][1], flat, pos, in_type)
        if p:
            new_params[idx] = {k: jnp.asarray(v) for k, v in p.items()}
        if st:
            new_state[idx] = {k: jnp.asarray(v) for k, v in st.items()}
        entries.append((cfg, layer_dicts[li][1], in_type))
        entry_model_idx.append(idx)
        li += 1
    if pos != flat.size:
        raise ValueError(
            f"coefficients.bin has {flat.size} values but the configuration "
            f"consumes {pos} — layer/param layout mismatch")
    model.params = tuple(new_params)
    model.state = tuple(new_state)
    new_opt = [u.init(p) for u, p in zip(model._updaters, model.params)]
    if updater_bin:
        # restore optimizer accumulators (ModelSerializer.java:109-127) so
        # training resumes with the reference's Adam moments etc.
        from deeplearning4j_tpu.train.updaters import normalize_updater
        uflat = read_nd4j(io.BytesIO(updater_bin)).ravel().astype(np.float32)
        gspec = normalize_updater(model.conf.updater)
        mapped = _consume_updater_state(entries, uflat, gspec)
        for li2, accs in mapped.items():
            idx = entry_model_idx[li2]
            new_opt[idx] = _merge_opt_state(new_opt[idx], accs)
    model.opt_state = tuple(new_opt)
    model.iteration = int(confs[0].get("iterationCount", 0) or 0)
    model.weights_imported = True
    return model


def _parse_cg_conf(conf: dict):
    """DL4J CG JSON -> (inputs, outputs, vertex_inputs, vertices) where
    ``vertices`` is an ordered dict name -> (vertex_type, body) preserving the
    JSON/insertion order that defines the reference's vertex numbering."""
    inputs = list(conf.get("networkInputs") or [])
    outputs = list(conf.get("networkOutputs") or [])
    vertex_inputs: Dict[str, List[str]] = {
        k: list(v) for k, v in (conf.get("vertexInputs") or {}).items()}
    raw = conf.get("vertices") or {}
    if not inputs or not outputs:
        raise ValueError("CG config lacks networkInputs/networkOutputs")
    vertices: Dict[str, Tuple[str, dict]] = {}
    for name, vd in raw.items():
        if not isinstance(vd, dict) or len(vd) != 1:
            raise ValueError(f"unparseable vertex {name!r}: {vd!r}")
        vtype = next(iter(vd))
        vertices[name] = (vtype, vd[vtype] or {})
    return inputs, outputs, vertex_inputs, vertices


def _dl4j_topo_order(inputs: List[str], vertex_names: List[str],
                     vertex_inputs: Dict[str, List[str]]) -> List[str]:
    """The reference's exact topological walk (ComputationGraph.java:1211-1300):
    vertex indices = networkInputs order then config-vertex insertion order;
    Kahn's algorithm with a FIFO queue, seeded with the zero-in-degree
    vertices in ascending index order, and each popped vertex's outputs
    relaxed in ascending index order."""
    from collections import deque

    names = list(inputs) + list(vertex_names)
    idx = {n: i for i, n in enumerate(names)}
    in_edges: Dict[int, set] = {i: set() for i in range(len(names))}
    out_edges: Dict[int, set] = {i: set() for i in range(len(names))}
    for n in vertex_names:
        for s in vertex_inputs.get(n, []):
            if s not in idx:
                raise ValueError(f"vertex {n!r} has unknown input {s!r}")
            in_edges[idx[n]].add(idx[s])
            out_edges[idx[s]].add(idx[n])
    queue = deque(i for i in range(len(names)) if not in_edges[i])
    order: List[int] = []
    while queue:
        nxt = queue.popleft()
        order.append(nxt)
        for v in sorted(out_edges[nxt]):
            in_edges[v].discard(nxt)
            if not in_edges[v]:
                queue.append(v)
    if len(order) != len(names):
        left = [names[i] for i, s in in_edges.items() if s]
        raise ValueError(f"cycle detected in CG config involving {left}")
    return [names[i] for i in order]


def _vertex_preproc(body: dict) -> Optional[Tuple[str, dict]]:
    """LayerVertex 'preProcessor' (InputPreProcessor.java:39-50
    WRAPPER_OBJECT names) -> (name, fields) or None."""
    pp = body.get("preProcessor")
    if isinstance(pp, dict) and len(pp) == 1:
        n = next(iter(pp))
        return n, (pp[n] or {})
    return None


def _pp_hwc(fields: dict) -> Optional[Tuple[int, int, int]]:
    h = fields.get("inputHeight") or fields.get("numRows")
    w = fields.get("inputWidth") or fields.get("numColumns")
    c = fields.get("numChannels")
    if h and w and c:
        return int(h), int(w), int(c)
    return None


_FF_LAYER_TYPES = ("dense", "output", "embedding", "loss", "activation", "dropout")
_RNN_LAYER_TYPES = ("gravesLSTM", "LSTM", "SimpleRnn", "rnnoutput")
_CNN_LAYER_TYPES = ("convolution", "subsampling", "batchNormalization",
                    "localResponseNormalization")


def _layer_of(body: dict) -> Tuple[str, dict]:
    layer_wrap = (body.get("layerConf") or {}).get("layer") or {}
    if len(layer_wrap) != 1:
        raise ValueError(f"unparseable LayerVertex layerConf {body!r}")
    t = next(iter(layer_wrap))
    return t, layer_wrap[t]


def _infer_cg_input_types(parsed, build_fn) -> List[InputType]:
    """Reconstruct the per-input InputTypes a DL4J CG conf does NOT serialize
    (ComputationGraphConfiguration keeps networkInputTypes builder-side only,
    ComputationGraphConfiguration.java:556,921 — but GraphBuilder.setInputTypes
    leaves two recoverable traces: nIn on every layer and InputPreProcessors
    embedded in LayerVertex JSON).

    Strategy per input: (a) a direct consumer's preProcessor names the type
    outright (cnnToFeedForward => conv(h,w,c); feedForwardToCnn =>
    ff of h*w*c); (b) a direct ff/rnn layer consumer's nIn; (c) conv-family
    consumer: channels = conv nIn, then scan square sizes s=1..512, building
    the (uninitialized) graph per candidate and accepting the first s whose
    resolved flatten points agree with every stored cnnToFeedForward dim /
    dense-after-conv nIn in the conf. Ambiguity or no constraint => raise,
    asking for an explicit input_type."""
    inputs, outputs, vertex_inputs, vertices = parsed

    consumers: Dict[str, List[str]] = {i: [] for i in inputs}
    for name in vertices:
        for s in vertex_inputs.get(name, []):
            if s in consumers:
                consumers[s].append(name)

    resolved: List[Optional[InputType]] = []
    unresolved_conv: List[Tuple[int, int]] = []  # (input index, channels)
    for ii, inp in enumerate(inputs):
        it: Optional[InputType] = None
        conv_channels = None
        for cname in consumers[inp]:
            vtype, body = vertices[cname]
            if vtype != "LayerVertex":
                continue
            pp = _vertex_preproc(body)
            t, d = _layer_of(body)
            n_in = int(d.get("nin") or d.get("nIn") or 0)
            if pp is not None:
                hwc = _pp_hwc(pp[1])
                if pp[0] == "cnnToFeedForward" and hwc:
                    it = InputType.convolutional(*hwc)
                    break
                if pp[0] == "feedForwardToCnn" and hwc:
                    it = InputType.convolutional_flat(*hwc)
                    break
                if pp[0] == "rnnToFeedForward" and n_in:
                    it = InputType.recurrent(n_in)
                    break
                if pp[0] == "feedForwardToRnn" and n_in:
                    it = InputType.feed_forward(n_in)
                    break
            if t in _RNN_LAYER_TYPES and n_in:
                it = InputType.recurrent(n_in)
                break
            if t in _FF_LAYER_TYPES and n_in:
                it = InputType.feed_forward(n_in)
                break
            if t == "convolution" and n_in:
                conv_channels = n_in
        if it is None and conv_channels is not None:
            unresolved_conv.append((ii, conv_channels))
        resolved.append(it)

    missing = [inputs[i] for i, it in enumerate(resolved)
               if it is None and i not in [u[0] for u in unresolved_conv]]
    if missing:
        raise ValueError(
            f"cannot infer InputType for CG inputs {missing} — pass "
            "input_type= (one InputType per network input)")

    if not unresolved_conv:
        return resolved  # type: ignore[return-value]

    def _flatten_constraints_ok(model) -> int:
        """#constraints checked, or -1 on any mismatch."""
        checks = 0
        for name, (vtype, body) in vertices.items():
            if vtype != "LayerVertex":
                continue
            rt = model.rt.get(name)
            if rt is None:
                return -1
            src_t = model.vertex_types.get(rt.inputs[0])
            pp = _vertex_preproc(body)
            if pp is not None and pp[0] == "cnnToFeedForward":
                hwc = _pp_hwc(pp[1])
                if hwc:
                    checks += 1
                    if (src_t is None or src_t.kind != "conv" or
                            (src_t.height, src_t.width, src_t.channels) != hwc):
                        return -1
                    continue
            if rt.pre is not None and src_t is not None and src_t.kind == "conv":
                t, d = _layer_of(body)
                n_in = int(d.get("nin") or d.get("nIn") or 0)
                if n_in:
                    checks += 1
                    if src_t.flat_size() != n_in:
                        return -1
        return checks

    matches: List[List[InputType]] = []
    match_sizes: List[int] = []
    first_build_error: Optional[Exception] = None
    any_built = False
    for s in range(1, 513):
        cand = list(resolved)
        for ii, ch in unresolved_conv:
            cand[ii] = InputType.convolutional(s, s, ch)
        try:
            model = build_fn(cand, init=False)
        except Exception as e:  # most candidates legitimately fail shape checks
            if first_build_error is None:
                first_build_error = e
            continue
        any_built = True
        checks = _flatten_constraints_ok(model)
        if checks > 0:
            matches.append(cand)
            match_sizes.append(s)
    if len(matches) == 1:
        return matches[0]  # type: ignore[return-value]
    names = [inputs[i] for i, _ in unresolved_conv]
    if len(matches) > 1:
        raise ValueError(
            f"ambiguous conv input size for CG inputs {names}: sizes "
            f"{match_sizes} all satisfy the conf's flatten constraints — "
            "pass input_type= (one InputType per network input)")
    if not any_built and first_build_error is not None:
        # every candidate failed identically: a size-INDEPENDENT config
        # problem — surface it instead of blaming the missing input size
        raise first_build_error
    raise ValueError(
        f"cannot infer the conv input height/width for CG inputs {names}: "
        "no stored InputPreProcessor or dense-nIn flatten constraint pins "
        "the size — pass input_type= (one InputType per network input)")


def _map_cg_weights(model, parsed, flat: np.ndarray,
                    updater_flat: Optional[np.ndarray] = None):
    """Split coefficients.bin by the reference's topological walk and map
    each LayerVertex segment into our per-vertex param/state dicts. When
    ``updater_flat`` is given, also restore optimizer accumulators
    (ComputationGraphUpdater.getOrderedLayers walks the same topo order)."""
    import jax.numpy as jnp

    inputs, outputs, vertex_inputs, vertices = parsed
    order = _dl4j_topo_order(inputs, list(vertices), vertex_inputs)
    input_set = set(inputs)
    pos = 0
    entries = []       # (cfg, layer_json, in_type) in flatten order
    entry_names = []
    for name in order:
        if name in input_set:
            continue
        vtype, body = vertices[name]
        if vtype != "LayerVertex":
            continue  # all supported non-layer vertices are parameter-free
        t, d = _layer_of(body)
        rt = model.rt[name]
        in_t = rt.input_types[0]
        src_t = model.vertex_types.get(rt.inputs[0])
        # dense-after-conv needs the CONV shape for the (c,h,w)->(h,w,c)
        # flatten permutation, which our auto-preprocessor hides
        if rt.pre is not None and src_t is not None and src_t.kind == "conv":
            in_t = src_t
        p, st, pos = _map_layer_params(rt.config, d, flat, pos, in_t)
        if p:
            model.params[name] = {k: jnp.asarray(v) for k, v in p.items()}
        if st:
            model.state[name] = {k: jnp.asarray(v) for k, v in st.items()}
        entries.append((rt.config, d, in_t))
        entry_names.append(name)
    if pos != flat.size:
        raise ValueError(
            f"coefficients.bin has {flat.size} values but the CG configuration "
            f"consumes {pos} — vertex/param layout mismatch")
    model.opt_state = {
        name: u.init(model.params[name]) for name, u in model._updaters.items()}
    if updater_flat is not None and updater_flat.size:
        from deeplearning4j_tpu.train.updaters import normalize_updater
        gspec = normalize_updater(model.conf.updater)
        mapped = _consume_updater_state(entries, updater_flat, gspec)
        for li, accs in mapped.items():
            name = entry_names[li]
            model.opt_state[name] = _merge_opt_state(model.opt_state[name], accs)


def _import_dl4j_graph_conf(conf: dict, input_type, parsed=None):
    """DL4J ComputationGraphConfiguration JSON -> our ComputationGraph
    (freshly initialized). Vertex dialect: conf/graph/GraphVertex.java:40-52
    WRAPPER_OBJECT names; layer vertices wrap a NeuralNetConfiguration."""
    if parsed is None:
        parsed = _parse_cg_conf(conf)
    inputs, outputs, vertex_inputs, vertices = parsed

    def build(its, init=True):
        return _build_cg(inputs, outputs, vertex_inputs, vertices, its, init)

    if input_type is None:
        its = _infer_cg_input_types(parsed, build)
    else:
        its = list(input_type) if isinstance(input_type, (list, tuple)) else [input_type]
    return build(its, init=True)


def _build_cg(inputs, outputs, vertex_inputs, vertices, its, init=True):
    from deeplearning4j_tpu.nn.graph import (
        ComputationGraph,
        ComputationGraphConfiguration,
        ElementWiseVertex,
        MergeVertex,
        SubsetVertex,
    )

    g = ComputationGraphConfiguration.builder().add_inputs(*inputs)
    g.set_input_types(*its)

    from deeplearning4j_tpu.nn.graph import (
        L2NormalizeVertex,
        L2Vertex,
        ScaleVertex,
        ShiftVertex,
        StackVertex,
        UnstackVertex,
    )

    def make_vertex(vtype: str, body: dict):
        if vtype == "MergeVertex":
            return MergeVertex()
        if vtype == "ElementWiseVertex":
            return ElementWiseVertex(op=str(body.get("op", "Add")).lower())
        if vtype == "SubsetVertex":
            return SubsetVertex(from_index=int(body.get("from", 0)),
                                to_index=int(body.get("to", 0)))
        if vtype == "ScaleVertex":
            return ScaleVertex(scale=float(body.get("scaleFactor", 1.0)))
        if vtype == "ShiftVertex":
            return ShiftVertex(shift=float(body.get("shiftFactor", 0.0)))
        if vtype == "StackVertex":
            return StackVertex()
        if vtype == "UnstackVertex":
            return UnstackVertex(from_index=int(body.get("from", 0)),
                                 stack_size=int(body.get("stackSize", 1)))
        if vtype == "L2Vertex":
            return L2Vertex(eps=float(body.get("eps", 1e-8)))
        if vtype == "L2NormalizeVertex":
            return L2NormalizeVertex(eps=float(body.get("eps", 1e-8)))
        raise ValueError(f"DL4J graph vertex type {vtype!r} not supported")

    # vertexInputs preserves the reference's insertion order (LinkedHashMap);
    # add vertices in an order where inputs precede consumers
    added = set(inputs)
    pending = [n for n in vertex_inputs if n not in inputs]
    updater = None
    while pending:
        progressed = False
        for name in list(pending):
            ins = vertex_inputs.get(name, [])
            if any(i not in added for i in ins):
                continue
            if name not in vertices:
                raise ValueError(f"vertexInputs names unknown vertex {name!r}")
            vtype, body = vertices[name]
            if vtype == "LayerVertex":
                t, d = _layer_of(body)
                g.add_layer(name, dl4j_layer_to_config(t, d), *ins)
                if updater is None:
                    updater = _parse_updater(d)
            else:
                g.add_vertex(name, make_vertex(vtype, body), *ins)
            added.add(name)
            pending.remove(name)
            progressed = True
        if not progressed:
            raise ValueError(f"cyclic or dangling vertex inputs: {pending}")
    g.set_outputs(*outputs)
    g.updater(updater or {"type": "sgd", "lr": 0.1})
    model = ComputationGraph(g.build())
    return model.init() if init else model


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------

def _export_layer(cfg, params: dict, state: dict, in_type: InputType) -> Tuple[Optional[dict], np.ndarray]:
    """(DL4J layer JSON object or None for preprocessors, flat segment)."""
    from deeplearning4j_tpu.nn import layers as L

    def act_json(a):
        # Keyed by OUR registered activation names; unmapped names must fail
        # loudly rather than silently exporting a different model.
        names = {"relu": "ReLU", "sigmoid": "Sigmoid", "tanh": "TanH",
                 "softmax": "Softmax", "identity": "Identity", "elu": "ELU",
                 "leakyrelu": "LReLU", "softplus": "SoftPlus",
                 "softsign": "SoftSign", "hardtanh": "HardTanh",
                 "hardsigmoid": "HardSigmoid", "selu": "SELU", "cube": "Cube",
                 "rationaltanh": "RationalTanh", "rectifiedtanh": "RectifiedTanh",
                 "swish": "Swish", "relu6": "ReLU6",
                 "thresholdedrelu": "ThresholdedReLU"}
        key = str(a).lower()
        if key not in names:
            raise ValueError(
                f"export_dl4j_zip: activation {a!r} has no DL4J equivalent")
        return {names[key]: {}}

    name = type(cfg).__name__
    seg = np.zeros((0,), np.float32)

    if isinstance(cfg, L.Conv2D) and not isinstance(cfg, L.Deconv2D):
        W = np.asarray(params["W"], np.float32)        # (kh,kw,in,out)
        kh, kw, n_in, n_out = W.shape
        pieces = []
        if cfg.has_bias:
            pieces.append(np.asarray(params["b"], np.float32).ravel())
        pieces.append(np.transpose(W, (3, 2, 0, 1)).ravel())  # C-order (out,in,kh,kw)
        seg = np.concatenate(pieces)
        d = {"nin": n_in, "nout": n_out, "kernelSize": [kh, kw],
             "stride": list(cfg.stride), "padding": list(cfg.padding),
             "convolutionMode": cfg.convolution_mode.capitalize(),
             "hasBias": cfg.has_bias, "activationFn": act_json(cfg.activation)}
        return {"convolution": d}, seg

    if isinstance(cfg, L.Subsampling2D):
        d = {"kernelSize": list(cfg.kernel), "stride": list(cfg.stride),
             "padding": list(cfg.padding),
             "convolutionMode": cfg.convolution_mode.capitalize(),
             "poolingType": cfg.pooling.upper()}
        return {"subsampling": d}, seg

    if isinstance(cfg, L.BatchNorm):
        pieces = []
        n = in_type.channels if in_type.kind == "conv" else in_type.flat_size()
        if cfg.use_gamma_beta:
            pieces += [np.asarray(params["gamma"], np.float32).ravel(),
                       np.asarray(params["beta"], np.float32).ravel()]
        pieces += [np.asarray(state["mean"], np.float32).ravel(),
                   np.asarray(state["var"], np.float32).ravel()]
        seg = np.concatenate(pieces)
        d = {"nin": n, "nout": n, "decay": cfg.decay, "eps": cfg.eps,
             "lockGammaBeta": not cfg.use_gamma_beta,
             "activationFn": act_json("identity")}
        return {"batchNormalization": d}, seg

    if isinstance(cfg, (L.GravesLSTM, L.LSTM)):
        graves = isinstance(cfg, L.GravesLSTM)
        Wx = np.asarray(params["Wx"], np.float32)
        Wh = np.asarray(params["Wh"], np.float32)
        b = np.asarray(params["b"], np.float32)
        n_in, H4 = Wx.shape
        H = H4 // 4
        wx = np.empty_like(Wx)
        rw_cols = 4 * H + (3 if graves else 0)
        rw = np.zeros((H, rw_cols), np.float32)
        bb = np.empty_like(b)
        for ours, theirs in _lstm_block_perm(H):
            wx[:, theirs * H:(theirs + 1) * H] = Wx[:, ours * H:(ours + 1) * H]
            rw[:, theirs * H:(theirs + 1) * H] = Wh[:, ours * H:(ours + 1) * H]
            bb[theirs * H:(theirs + 1) * H] = b[ours * H:(ours + 1) * H]
        if graves:
            p = np.asarray(params["peephole"], np.float32)
            rw[:, 4 * H] = p[H:2 * H]       # wFF <- p_f
            rw[:, 4 * H + 1] = p[2 * H:]    # wOO <- p_o
            rw[:, 4 * H + 2] = p[:H]        # wGG <- p_i
        seg = np.concatenate([wx.ravel(order="F"), rw.ravel(order="F"), bb])
        d = {"nin": n_in, "nout": H, "forgetGateBiasInit": cfg.forget_gate_bias_init,
             "activationFn": act_json(cfg.activation)}
        return {"gravesLSTM" if graves else "LSTM": d}, seg

    if name in ("Dense", "OutputLayer", "RnnOutputLayer", "Embedding"):
        W = np.asarray(params["W"], np.float32)
        n_in, n_out = W.shape
        if in_type.kind == "conv":
            H_, W_, C_ = in_type.height, in_type.width, in_type.channels
            inv = np.arange(n_in).reshape(H_, W_, C_).transpose(2, 0, 1).ravel()
            W = W[inv]
        has_bias = bool(getattr(cfg, "has_bias", True)) and "b" in params
        pieces = [W.ravel(order="F")]
        if has_bias:
            pieces.append(np.asarray(params["b"], np.float32).ravel())
        seg = np.concatenate(pieces)
        d = {"nin": n_in, "nout": n_out, "hasBias": has_bias,
             "activationFn": act_json(cfg.activation)}
        t = {"Dense": "dense", "OutputLayer": "output",
             "RnnOutputLayer": "rnnoutput", "Embedding": "embedding"}[name]
        if t in ("output", "rnnoutput"):
            loss_names = {"mcxent": "MCXENT", "mse": "MSE", "xent": "BinaryXENT",
                          "l1": "L1", "l2": "L2", "mae": "MAE", "msle": "MSLE",
                          "mape": "MAPE", "hinge": "Hinge",
                          "squared_hinge": "SquaredHinge", "poisson": "Poisson",
                          "kld": "KLD", "cosine_proximity": "CosineProximity"}
            key = str(cfg.loss).lower()
            if key not in loss_names:
                raise ValueError(
                    f"export_dl4j_zip: loss {cfg.loss!r} has no DL4J equivalent")
            d["lossFn"] = {"@class": "org.nd4j.linalg.lossfunctions.impl.Loss"
                           + loss_names[key]}
        return {t: d}, seg

    if isinstance(cfg, L.ActivationLayer):
        return {"activation": {"activationFn": act_json(cfg.activation)}}, seg
    if isinstance(cfg, L.DropoutLayer):
        return {"dropout": {"dropOut": 1.0 - cfg.dropout}}, seg
    if isinstance(cfg, L.LocalResponseNormalization):
        return {"localResponseNormalization": {
            "k": cfg.k, "n": cfg.n, "alpha": cfg.alpha, "beta": cfg.beta}}, seg
    if isinstance(cfg, L.GlobalPooling):
        return {"GlobalPooling": {"poolingType": cfg.pooling.upper()}}, seg
    raise ValueError(f"export_dl4j_zip: layer {name} not supported")


def _export_layer_spec(cfg, gspec: dict) -> dict:
    """The canonical updater spec a layer's trainable vars use on export:
    per-layer override first (LayerConfig.updater), else the model global;
    frozen layers are NoOp."""
    if not getattr(cfg, "trainable", True):
        return _canon_spec({"type": "noop"})
    lspec = getattr(cfg, "updater", None)
    return _canon_spec(lspec) if lspec else gspec


def _export_updater_state(model, export_entries) -> np.ndarray:
    """Flatten optimizer accumulators into the reference's updater-block
    layout (inverse of ``_consume_updater_state``). ``export_entries``:
    ordered [(cfg, in_type, model_idx)]."""
    gspec = _canon_spec(model.conf.updater)

    def spec_for(li):
        return _export_layer_spec(export_entries[li][0], gspec)

    # per-(entry, var) accumulator segments in DL4J per-layer layout
    seg_of: Dict[Tuple[int, int, str], np.ndarray] = {}
    for li, (cfg, in_type, idx) in enumerate(export_entries):
        sizes = _dl4j_var_sizes(cfg, in_type)
        opt = model.opt_state[idx]
        keys = _spec_state_keys(spec_for(li))
        if keys and isinstance(opt, dict):
            for key in keys:
                tree = opt.get(key)
                if tree is None:
                    continue
                # accumulators flatten exactly like the params themselves;
                # BN mean/var (stats) have no accumulator — zero-filled here
                # and dropped below
                np_tree = {k: np.asarray(v, np.float32) for k, v in tree.items()}
                zero_state = {k: np.zeros(np.shape(v), np.float32)
                              for k, v in (model.state[idx] or {}).items()}
                _, seg = _export_layer(cfg, np_tree, zero_state, in_type)
                off = 0
                for vi, (kind, size) in enumerate(sizes):
                    if kind == "train":
                        seg_of[(li, vi, key)] = seg[off:off + size]
                    off += size

    _, blocks = _updater_var_blocks(
        [(cfg, it) for cfg, it, _idx in export_entries], spec_for)
    pieces = []
    for spec, recs in blocks:
        for key in _spec_state_keys(spec):
            for li, vi, size, _, _ in recs:
                pieces.append(seg_of.get((li, vi, key),
                                         np.zeros(size, np.float32)))
    return (np.concatenate(pieces).astype(np.float32)
            if pieces else np.zeros((0,), np.float32))


def _vertex_to_dl4j_json(v) -> dict:
    """Inverse of ``_build_cg.make_vertex`` (conf/graph/GraphVertex.java
    WRAPPER_OBJECT names)."""
    name = type(v).__name__
    if name == "MergeVertex":
        return {"MergeVertex": {}}
    if name == "ElementWiseVertex":
        return {"ElementWiseVertex": {"op": str(v.op).capitalize()}}
    if name == "SubsetVertex":
        return {"SubsetVertex": {"from": v.from_index, "to": v.to_index}}
    if name == "ScaleVertex":
        return {"ScaleVertex": {"scaleFactor": v.scale}}
    if name == "ShiftVertex":
        return {"ShiftVertex": {"shiftFactor": v.shift}}
    if name == "StackVertex":
        return {"StackVertex": {}}
    if name == "UnstackVertex":
        return {"UnstackVertex": {"from": v.from_index,
                                  "stackSize": v.stack_size}}
    if name == "L2Vertex":
        return {"L2Vertex": {"eps": v.eps}}
    if name == "L2NormalizeVertex":
        return {"L2NormalizeVertex": {"eps": v.eps}}
    raise ValueError(
        f"export_dl4j_zip: graph vertex {name} has no DL4J equivalent")


def _export_cg_zip(model, path: str):
    """ComputationGraph -> reference CG zip: vertices emitted in topological
    order (so the reference's vertex numbering and param-flattening walk —
    see ``_dl4j_topo_order`` — reproduce this exporter's segment order),
    LayerVertices carrying cnnToFeedForward preProcessors where our resolver
    inserted one (which is also what makes re-import's input-type inference
    work), plus coefficients.bin and updaterState.bin."""
    conf = model.conf
    gspec = _canon_spec(conf.updater)
    inputs = list(conf.inputs)
    vertices_json: Dict[str, dict] = {}
    vertex_inputs: Dict[str, list] = {}

    def layer_in_type(rt):
        """(the in_type _export_layer uses, the preProcessor JSON to store
        — WRAPPER_OBJECT names the importer and the reference both read)."""
        in_type = rt.input_types[0]
        src_t = model.vertex_types.get(rt.inputs[0])
        if rt.pre is None or src_t is None:
            return in_type, None
        pname = type(rt.pre).__name__
        if pname == "CnnToFeedForward":
            # dense-after-conv: the flatten permutation needs the CONV shape
            return src_t, {"cnnToFeedForward": {
                "inputHeight": src_t.height, "inputWidth": src_t.width,
                "numChannels": src_t.channels}}
        if pname == "FeedForwardToCnn":
            return in_type, {"feedForwardToCnn": {
                "inputHeight": in_type.height, "inputWidth": in_type.width,
                "numChannels": in_type.channels}}
        if pname == "RnnToFeedForward":
            return in_type, {"rnnToFeedForward": {}}
        if pname == "FeedForwardToRnn":
            return in_type, {"feedForwardToRnn": {}}
        if pname == "CnnToRnn":
            return in_type, {"cnnToRnn": {
                "inputHeight": src_t.height, "inputWidth": src_t.width,
                "numChannels": src_t.channels}}
        if pname == "RnnToCnn":
            return in_type, {"rnnToCnn": {
                "inputHeight": in_type.height, "inputWidth": in_type.width,
                "numChannels": in_type.channels}}
        raise ValueError(
            f"export_dl4j_zip: auto-inserted preprocessor {pname} has no "
            "DL4J InputPreProcessor equivalent")

    # pass 1: the conf JSON, vertices keyed in our topological order; the
    # per-vertex flat segment is cached so pass 2 only reorders
    seg_of: Dict[str, np.ndarray] = {}
    entry_of: Dict[str, tuple] = {}
    for name in model.topo_order:
        rt = model.rt[name]
        vertex_inputs[name] = list(rt.inputs)
        if not rt.spec.is_layer():
            vertices_json[name] = _vertex_to_dl4j_json(rt.config)
            continue
        in_type, pp = layer_in_type(rt)
        obj, seg = _export_layer(rt.config, model.params.get(name) or {},
                                 model.state.get(name) or {}, in_type)
        if obj is None:
            raise ValueError(
                f"export_dl4j_zip: CG vertex {name!r} produced no DL4J layer")
        t = next(iter(obj))
        if _dl4j_var_sizes(rt.config, in_type):
            obj[t].setdefault(
                "iUpdater",
                _updater_to_dl4j_json(_export_layer_spec(rt.config, gspec)))
        lv: Dict[str, Any] = {"layerConf": {
            "layer": obj,
            "iterationCount": int(getattr(model, "iteration", 0))}}
        if pp is not None:
            lv["preProcessor"] = pp
        vertices_json[name] = {"LayerVertex": lv}
        seg_of[name] = seg
        entry_of[name] = (rt.config, in_type, name)

    # pass 2: coefficients in the order the IMPORTER (and the reference
    # runtime) will consume them — the Kahn walk over the numbering the
    # JSON defines, which is NOT always our emission order (two valid
    # topological orders of the same DAG can differ)
    ref_order = _dl4j_topo_order(inputs, list(vertices_json), vertex_inputs)
    segs = [seg_of[n] for n in ref_order if n in seg_of]
    export_entries = [entry_of[n] for n in ref_order if n in entry_of]

    conf_json = {
        "networkInputs": inputs,
        "networkOutputs": list(conf.outputs),
        "vertexInputs": vertex_inputs,
        "vertices": vertices_json,
    }
    flat = np.concatenate(segs) if segs else np.zeros((0,), np.float32)
    buf = io.BytesIO()
    write_nd4j(buf, flat[None, :], "FLOAT")
    ustate = _export_updater_state(model, export_entries)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(conf_json))
        zf.writestr("coefficients.bin", buf.getvalue())
        if ustate.size:
            ubuf = io.BytesIO()
            write_nd4j(ubuf, ustate[None, :], "FLOAT")
            zf.writestr("updaterState.bin", ubuf.getvalue())


def export_dl4j_zip(model, path: str):
    """Write a MultiLayerNetwork OR ComputationGraph in the reference's zip
    format (configuration.json + coefficients.bin + updaterState.bin) so
    DL4J can load our models and resume training with the optimizer state
    intact."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    if isinstance(model, ComputationGraph):
        return _export_cg_zip(model, path)
    mlc = model.conf
    gspec = _canon_spec(mlc.updater)
    confs = []
    segs = []
    export_entries = []  # (cfg, in_type, model layer idx)
    for idx, cfg in enumerate(model.layers):
        if type(cfg).__module__.endswith("preprocessors"):
            continue
        in_type = model.layer_input_types[idx]
        if idx > 0 and type(model.layers[idx - 1]).__module__.endswith("preprocessors"):
            pre_in = model.layer_input_types[idx - 1]
            if pre_in.kind == "conv":
                in_type = pre_in
        obj, seg = _export_layer(cfg, model.params[idx] or {},
                                 model.state[idx] or {}, in_type)
        if obj is not None:
            t = next(iter(obj))
            if _dl4j_var_sizes(cfg, in_type):
                # frozen layers export iUpdater NoOp so the import side
                # segments updaterState.bin identically (no accumulators)
                obj[t].setdefault(
                    "iUpdater", _updater_to_dl4j_json(_export_layer_spec(cfg, gspec)))
            confs.append({"layer": obj, "seed": mlc.seed,
                          "iterationCount": int(getattr(model, "iteration", 0))})
            segs.append(seg)
            export_entries.append((cfg, in_type, idx))

    preprocs = {}
    it = mlc.input_type
    if it is not None and it.kind in ("conv", "conv_flat"):
        preprocs["0"] = {"feedForwardToCnn": {
            "inputHeight": it.height, "inputWidth": it.width,
            "numChannels": it.channels}}

    conf_json = {
        "backprop": True, "pretrain": False,
        "backpropType": "TruncatedBPTT" if mlc.backprop_type == "tbptt" else "Standard",
        "tbpttFwdLength": mlc.tbptt_fwd_length, "tbpttBackLength": mlc.tbptt_back_length,
        "confs": confs, "inputPreProcessors": preprocs,
    }
    flat = np.concatenate(segs) if segs else np.zeros((0,), np.float32)
    buf = io.BytesIO()
    write_nd4j(buf, flat[None, :], "FLOAT")
    ustate = _export_updater_state(model, export_entries)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(conf_json))
        zf.writestr("coefficients.bin", buf.getvalue())
        if ustate.size:
            ubuf = io.BytesIO()
            write_nd4j(ubuf, ustate[None, :], "FLOAT")
            zf.writestr("updaterState.bin", ubuf.getvalue())
