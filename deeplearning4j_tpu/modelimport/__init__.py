"""Keras model import (deeplearning4j-modelimport parity).

Reference: deeplearning4j-modelimport/src/main/java/org/deeplearning4j/nn/
modelimport/keras/KerasModelImport.java:50-121 (importKerasSequentialModel*
-> MultiLayerNetwork, importKerasModel* -> ComputationGraph), Hdf5Archive.java:46,
per-layer converters under layers/**.
"""

from deeplearning4j_tpu.modelimport.keras import (
    InvalidKerasConfigurationError,
    KerasModelImport,
    UnsupportedKerasConfigurationError,
)

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationError",
    "UnsupportedKerasConfigurationError",
]
