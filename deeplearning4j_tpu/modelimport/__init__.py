"""Keras model import (deeplearning4j-modelimport parity).

Reference: deeplearning4j-modelimport/src/main/java/org/deeplearning4j/nn/
modelimport/keras/KerasModelImport.java:50-121 (importKerasSequentialModel*
-> MultiLayerNetwork, importKerasModel* -> ComputationGraph), Hdf5Archive.java:46,
per-layer converters under layers/**.
"""

from deeplearning4j_tpu.modelimport.keras import (
    InvalidKerasConfigurationError,
    KerasModelImport,
    UnsupportedKerasConfigurationError,
)
from deeplearning4j_tpu.modelimport.dl4j import export_dl4j_zip, import_dl4j_zip

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationError",
    "UnsupportedKerasConfigurationError",
    "import_dl4j_zip",
    "export_dl4j_zip",
    "import_model",
]


def import_model(path: str):
    """Format-detecting loader: Keras HDF5 (``.h5``/``.hdf5``/``.keras``)
    via :class:`KerasModelImport` (Sequential vs functional auto-detected)
    or DL4J zip via :func:`import_dl4j_zip`. The serving tier's model
    registry loads everything through here so one path string is all a
    deployment manifest needs."""
    lower = str(path).lower()
    if lower.endswith((".h5", ".hdf5", ".keras")):
        return KerasModelImport.import_keras_model(path)
    if lower.endswith(".zip"):
        return import_dl4j_zip(path)
    raise ValueError(
        f"unrecognized model format: {path!r} (expected .h5/.hdf5/.keras "
        "for Keras or .zip for DL4J)")
