"""Keras model import (deeplearning4j-modelimport parity).

Reference: deeplearning4j-modelimport/src/main/java/org/deeplearning4j/nn/
modelimport/keras/KerasModelImport.java:50-121 (importKerasSequentialModel*
-> MultiLayerNetwork, importKerasModel* -> ComputationGraph), Hdf5Archive.java:46,
per-layer converters under layers/**.
"""

from deeplearning4j_tpu.modelimport.keras import (
    InvalidKerasConfigurationError,
    KerasModelImport,
    UnsupportedKerasConfigurationError,
)
from deeplearning4j_tpu.modelimport.dl4j import export_dl4j_zip, import_dl4j_zip

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationError",
    "UnsupportedKerasConfigurationError",
    "import_dl4j_zip",
    "export_dl4j_zip",
]
