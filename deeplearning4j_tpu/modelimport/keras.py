"""Keras HDF5 model import.

Capability parity with the reference's deeplearning4j-modelimport module:
KerasModelImport.java:50-121 (entry points), KerasSequentialModel /
KerasModel (config parsing), utils/KerasModelUtils (weight setting), and the
per-layer converters under layers/** (~40 Keras layer classes; the ~17
load-bearing ones are implemented here).

TPU-first notes: Keras channels_last conventions (NHWC activations, HWIO
conv kernels, Dense [in,out] kernels, LSTM i/f/c/o gate blocks in
kernel/recurrent_kernel/bias) are ALSO this framework's native layouts, so
weights transfer without transposition — unlike the reference, which
permutes every kernel into NCHW buffers (KerasConvolutionUtils).

The HDF5 container is read with h5py when available; model-config JSON can
also be imported alone (importKerasModelConfiguration parity).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nn.graph import (
    ComputationGraph,
    ComputationGraphConfiguration,
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNorm,
    Conv1D,
    Conv2D,
    Dense,
    DepthwiseConv2D,
    DropoutLayer,
    EmbeddingSequence,
    GlobalPooling,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SeparableConv2D,
    SimpleRnn,
    Subsampling1D,
    Subsampling2D,
    Upsampling2D,
    ZeroPadding2D,
)
from deeplearning4j_tpu.nn.model import MultiLayerConfiguration, MultiLayerNetwork


class InvalidKerasConfigurationError(ValueError):
    """Malformed Keras config (reference exceptions/InvalidKerasConfigurationException)."""


class UnsupportedKerasConfigurationError(ValueError):
    """Keras feature with no converter (UnsupportedKerasConfigurationException)."""


# ---------------------------------------------------------------------------
# activation / padding translation
# ---------------------------------------------------------------------------

_ACTIVATIONS = {
    "linear": "identity",
    "relu": "relu",
    "relu6": "relu6",
    "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid",
    "tanh": "tanh",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "elu": "elu",
    "selu": "selu",
    "swish": "swish",
    "gelu": "gelu",
    "exponential": "exp",
    "leaky_relu": "leakyrelu",
}


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if name not in _ACTIVATIONS:
        raise UnsupportedKerasConfigurationError(f"activation {name!r}")
    return _ACTIVATIONS[name]


def _conv_mode(padding: str) -> Tuple[str, Tuple[int, int]]:
    if padding == "same":
        return "same", (0, 0)
    if padding == "valid":
        return "truncate", (0, 0)
    raise UnsupportedKerasConfigurationError(f"padding {padding!r}")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


# ---------------------------------------------------------------------------
# per-layer converters (Keras class_name -> LayerConfig)
# ---------------------------------------------------------------------------


def _loss_for(activation: str) -> str:
    return {"softmax": "mcxent", "sigmoid": "xent"}.get(activation, "mse")


def _keras1_normalize(class_name: str, cfg: dict) -> dict:
    """Accept the Keras-1 config dialect (reference
    config/Keras1LayerConfiguration.java): legacy field names are mapped to
    their Keras-2 equivalents before conversion."""
    cfg = dict(cfg)
    if "output_dim" in cfg:
        cfg.setdefault("units", cfg["output_dim"])
    if "nb_filter" in cfg:
        cfg.setdefault("filters", cfg["nb_filter"])
    if "nb_row" in cfg:
        cfg.setdefault("kernel_size", [cfg["nb_row"], cfg.get("nb_col", cfg["nb_row"])])
    if "filter_length" in cfg:
        cfg.setdefault("kernel_size", cfg["filter_length"])
    if "subsample" in cfg:
        cfg.setdefault("strides", cfg["subsample"])
    if "subsample_length" in cfg:
        cfg.setdefault("strides", cfg["subsample_length"])
    if "border_mode" in cfg:
        cfg.setdefault("padding", cfg["border_mode"])
    if "pool_length" in cfg:
        cfg.setdefault("pool_size", cfg["pool_length"])
    if "stride" in cfg and "strides" not in cfg:
        cfg.setdefault("strides", cfg["stride"])
    if "atrous_rate" in cfg:
        # Keras-1 AtrousConvolution1D/2D (reference
        # KerasAtrousConvolution1D/2D.java): dilation under a legacy name
        cfg.setdefault("dilation_rate", cfg["atrous_rate"])
    if class_name in ("Dropout", "GaussianDropout", "AlphaDropout") and "p" in cfg:
        cfg.setdefault("rate", cfg["p"])
    if class_name == "GaussianNoise" and "sigma" in cfg:
        cfg.setdefault("stddev", cfg["sigma"])
    return cfg


def _convert_layer(class_name: str, cfg: dict, *, as_output: bool = False,
                   recurrent: bool = False):
    """Returns a LayerConfig, or None for structural layers (Flatten,
    InputLayer) that this framework expresses as preprocessors."""
    cfg = _keras1_normalize(class_name, cfg)
    if class_name in ("InputLayer", "Flatten"):
        return None
    if class_name == "Dense":
        act = _act(cfg.get("activation"))
        if as_output:
            klass = RnnOutputLayer if recurrent else OutputLayer
            return klass(
                n_out=int(cfg["units"]), activation=act, loss=_loss_for(act),
                has_bias=bool(cfg.get("use_bias", True)),
            )
        return Dense(n_out=int(cfg["units"]), activation=act,
                     has_bias=bool(cfg.get("use_bias", True)))
    if class_name in ("Conv2D", "Convolution2D", "AtrousConvolution2D"):
        mode, pad = _conv_mode(cfg.get("padding", "valid"))
        return Conv2D(
            n_out=int(cfg["filters"]), kernel=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", 1)), dilation=_pair(cfg.get("dilation_rate", 1)),
            convolution_mode=mode, padding=pad,
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)),
        )
    if class_name in ("Conv1D", "Convolution1D", "AtrousConvolution1D"):
        mode, _ = _conv_mode(cfg.get("padding", "valid"))
        k = cfg.get("kernel_size", 3)
        s = cfg.get("strides", 1)
        d = cfg.get("dilation_rate", 1)
        return Conv1D(
            n_out=int(cfg["filters"]),
            kernel=int(k[0] if isinstance(k, (list, tuple)) else k),
            stride=int(s[0] if isinstance(s, (list, tuple)) else s),
            dilation=int(d[0] if isinstance(d, (list, tuple)) else d),
            convolution_mode=mode, activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)),
        )
    if class_name == "DepthwiseConv2D":
        mode, pad = _conv_mode(cfg.get("padding", "valid"))
        return DepthwiseConv2D(
            kernel=_pair(cfg.get("kernel_size", 3)), stride=_pair(cfg.get("strides", 1)),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=mode, padding=pad,
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)),
        )
    if class_name == "SeparableConv2D":
        mode, pad = _conv_mode(cfg.get("padding", "valid"))
        return SeparableConv2D(
            n_out=int(cfg["filters"]), kernel=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", 1)),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=mode, padding=pad,
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)),
        )
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        mode, pad = _conv_mode(cfg.get("padding", "valid"))
        return Subsampling2D(
            kernel=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            padding=pad, convolution_mode=mode,
            pooling="max" if class_name.startswith("Max") else "avg",
        )
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        mode, _ = _conv_mode(cfg.get("padding", "valid"))
        p = cfg.get("pool_size", 2)
        s = cfg.get("strides") or p
        return Subsampling1D(
            kernel=int(p[0] if isinstance(p, (list, tuple)) else p),
            stride=int(s[0] if isinstance(s, (list, tuple)) else s),
            convolution_mode=mode,
            pooling="max" if class_name.startswith("Max") else "avg",
        )
    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                      "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return GlobalPooling(pooling="max" if "Max" in class_name else "avg")
    if class_name == "BatchNormalization":
        return BatchNorm(
            eps=float(cfg.get("epsilon", 1e-3)),
            decay=float(cfg.get("momentum", 0.99)),
        )
    if class_name == "Activation":
        return ActivationLayer(activation=_act(cfg.get("activation")))
    if class_name == "Dropout":
        return DropoutLayer(dropout=float(cfg.get("rate", 0.5)))
    if class_name == "Reshape":
        from deeplearning4j_tpu.nn.preprocessors import Reshape

        return Reshape(shape=tuple(int(d) for d in cfg["target_shape"]))
    if class_name in ("SpatialDropout1D", "SpatialDropout2D"):
        from deeplearning4j_tpu.nn.layers import SpatialDropout

        return SpatialDropout(dropout=float(cfg.get("rate", 0.5)))
    if class_name == "ZeroPadding1D":
        from deeplearning4j_tpu.nn.layers import ZeroPadding1D

        # the dataclass normalizes int-or-(l,r) itself (_pads)
        return ZeroPadding1D(padding=cfg.get("padding", 1))
    if class_name == "Cropping1D":
        from deeplearning4j_tpu.nn.layers import Cropping1D

        return Cropping1D(crop=cfg.get("cropping", 1))
    if class_name == "UpSampling1D":
        from deeplearning4j_tpu.nn.layers import Upsampling1D

        return Upsampling1D(size=int(cfg.get("size", 2)))
    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", 1)
        if isinstance(pad, (list, tuple)) and isinstance(pad[0], (list, tuple)):
            # ((top,bottom),(left,right))
            return ZeroPadding2D(padding=(int(pad[0][0]), int(pad[0][1]),
                                          int(pad[1][0]), int(pad[1][1])))
        ph, pw = _pair(pad)
        return ZeroPadding2D(padding=(ph, ph, pw, pw))
    if class_name == "UpSampling2D":
        return Upsampling2D(size=_pair(cfg.get("size", 2)))
    if class_name == "Embedding":
        return EmbeddingSequence(n_in=int(cfg["input_dim"]),
                                 n_out=int(cfg["output_dim"]))
    if class_name == "LSTM":
        return LSTM(
            n_out=int(cfg["units"]), activation=_act(cfg.get("activation", "tanh")),
            gate_activation=_act(cfg.get("recurrent_activation", "sigmoid")),
        )
    if class_name == "GRU":
        from deeplearning4j_tpu.nn.layers import GRU

        return GRU(
            n_out=int(cfg["units"]), activation=_act(cfg.get("activation", "tanh")),
            gate_activation=_act(cfg.get("recurrent_activation", "sigmoid")),
            reset_after=bool(cfg.get("reset_after", True)),
        )
    if class_name == "SimpleRNN":
        return SimpleRnn(n_out=int(cfg["units"]),
                         activation=_act(cfg.get("activation", "tanh")))
    if class_name == "Conv2DTranspose":
        mode, pad = _conv_mode(cfg.get("padding", "valid"))
        from deeplearning4j_tpu.nn.layers import Deconv2D

        if cfg.get("output_padding") not in (None, 0, [0, 0], (0, 0)):
            raise UnsupportedKerasConfigurationError(
                f"Conv2DTranspose output_padding {cfg['output_padding']!r}")
        if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
            raise UnsupportedKerasConfigurationError(
                f"Conv2DTranspose dilation_rate {cfg['dilation_rate']!r}")
        return Deconv2D(
            n_out=int(cfg["filters"]), kernel=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", 1)),
            convolution_mode=mode, padding=pad,
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)),
        )
    if class_name == "Cropping2D":
        from deeplearning4j_tpu.nn.layers import Cropping2D

        c = cfg.get("cropping", 0)
        if isinstance(c, (list, tuple)) and c and isinstance(c[0], (list, tuple)):
            crop = (int(c[0][0]), int(c[0][1]), int(c[1][0]), int(c[1][1]))
        else:
            ch, cw = _pair(c)
            crop = (ch, ch, cw, cw)
        return Cropping2D(crop=crop)
    if class_name == "LeakyReLU":
        from deeplearning4j_tpu.nn.layers import LeakyReLULayer

        alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return LeakyReLULayer(alpha=float(alpha))
    if class_name == "ELU":
        from deeplearning4j_tpu.nn.layers import ELULayer

        return ELULayer(alpha=float(cfg.get("alpha", 1.0)))
    if class_name == "ThresholdedReLU":
        from deeplearning4j_tpu.nn.layers import ThresholdedReLULayer

        return ThresholdedReLULayer(theta=float(cfg.get("theta", 1.0)))
    if class_name == "PReLU":
        from deeplearning4j_tpu.nn.layers import PReLU

        if cfg.get("shared_axes"):
            raise UnsupportedKerasConfigurationError("PReLU shared_axes")
        return PReLU()
    if class_name == "Permute":
        from deeplearning4j_tpu.nn.layers import Permute

        return Permute(dims=tuple(int(d) for d in cfg["dims"]))
    if class_name == "RepeatVector":
        from deeplearning4j_tpu.nn.layers import RepeatVector

        return RepeatVector(n=int(cfg["n"]))
    if class_name == "GaussianNoise":
        from deeplearning4j_tpu.nn.layers import GaussianNoise

        return GaussianNoise(stddev=float(cfg["stddev"]))
    if class_name == "GaussianDropout":
        from deeplearning4j_tpu.nn.layers import GaussianDropout

        return GaussianDropout(rate=float(cfg["rate"]))
    if class_name == "AlphaDropout":
        from deeplearning4j_tpu.nn.layers import AlphaDropout

        return AlphaDropout(dropout=float(cfg["rate"]))
    if class_name == "Bidirectional":
        from deeplearning4j_tpu.nn.layers import Bidirectional

        inner_cfg = cfg["layer"]
        inner = _convert_layer(inner_cfg["class_name"],
                               inner_cfg.get("config", {}))
        mode = {"concat": "concat", "sum": "add", "ave": "average",
                "mul": "mul"}.get(cfg.get("merge_mode", "concat"))
        if mode is None:
            raise UnsupportedKerasConfigurationError(
                f"Bidirectional merge_mode {cfg.get('merge_mode')!r}")
        bidir = Bidirectional(rnn=inner, mode=mode)
        if not inner_cfg.get("config", {}).get("return_sequences", False):
            # Keras return_sequences=False: fwd LAST step ++ bwd FINAL state
            # (= step 0 after the flip-back) — a plain LastTimeStep would be
            # wrong for the backward half
            if mode != "concat":
                raise UnsupportedKerasConfigurationError(
                    "Bidirectional(return_sequences=False) with merge_mode "
                    f"{cfg.get('merge_mode')!r}")
            from deeplearning4j_tpu.nn.layers import BidirectionalLastTimeStep

            return BidirectionalLastTimeStep(rnn=bidir)
        return bidir
    raise UnsupportedKerasConfigurationError(f"Keras layer {class_name!r}")


_RETURNS_SEQUENCES = ("LSTM", "SimpleRNN", "GRU")


def _keras_input_type(shape: Sequence[Optional[int]],
                      first_class: str) -> InputType:
    """batch_input_shape (leading None) -> InputType."""
    dims = [d for d in shape[1:]]
    if len(dims) == 3:
        return InputType.convolutional(int(dims[0]), int(dims[1]), int(dims[2]))
    if len(dims) == 2:
        if first_class == "Conv1D":
            return InputType.recurrent(int(dims[1]), int(dims[0]))
        return InputType.recurrent(int(dims[1]), int(dims[0]))
    if len(dims) == 1:
        if first_class == "Embedding":
            # [B, T] integer sequence input
            return InputType.recurrent(1, int(dims[0]))
        return InputType.feed_forward(int(dims[0]))
    raise UnsupportedKerasConfigurationError(f"input shape {shape}")


# ---------------------------------------------------------------------------
# weight mapping
# ---------------------------------------------------------------------------


def _set_weights(layer_conf, keras_weights: List[np.ndarray], params: dict,
                 state: dict) -> Tuple[dict, dict]:
    """Map a Keras layer's weight list onto (params, state) dicts. Shapes are
    identical to ours (module docstring), so this is naming, not math."""
    import jax.numpy as jnp

    t = type(layer_conf).__name__
    w = [np.asarray(a) for a in keras_weights]
    p = dict(params)
    s = dict(state) if isinstance(state, dict) else state
    if t in ("Dense", "OutputLayer", "RnnOutputLayer", "Conv2D", "Conv1D",
             "SeparableConv2D"):
        if t == "SeparableConv2D":
            dw, pw = w[0], w[1]
            kh, kw_, in_c, mult = dw.shape
            p["dW"] = jnp.asarray(dw.reshape(kh, kw_, 1, in_c * mult))
            p["pW"] = jnp.asarray(pw)
            if len(w) > 2:
                p["b"] = jnp.asarray(w[2])
        else:
            p["W"] = jnp.asarray(w[0])
            if len(w) > 1:
                p["b"] = jnp.asarray(w[1])
    elif t == "DepthwiseConv2D":
        dw = w[0]
        kh, kw_, in_c, mult = dw.shape
        p["W"] = jnp.asarray(dw.reshape(kh, kw_, 1, in_c * mult))
        if len(w) > 1:
            p["b"] = jnp.asarray(w[1])
    elif t == "BatchNorm":
        p["gamma"] = jnp.asarray(w[0])
        p["beta"] = jnp.asarray(w[1])
        s = {"mean": jnp.asarray(w[2]), "var": jnp.asarray(w[3])}
    elif t == "Deconv2D":
        # Keras Conv2DTranspose kernel is (kh, kw, OUT, IN) with
        # gradient-of-conv semantics; lax.conv_transpose with HWIO
        # (transpose_kernel=False) consumes the kernel directly, so the
        # equivalent native kernel is the spatially-FLIPPED transpose
        k = w[0]
        p["W"] = jnp.asarray(np.flip(k, axis=(0, 1)).transpose(0, 1, 3, 2))
        if len(w) > 1:
            p["b"] = jnp.asarray(w[1])
    elif t == "PReLU":
        p["alpha"] = jnp.asarray(w[0])
    elif t == "Bidirectional":
        if len(w) != 6:
            raise UnsupportedKerasConfigurationError(
                f"Bidirectional expects 6 weight arrays, got {len(w)}")

        def _dir(kernel, rec, bias):
            inner = type(layer_conf.rnn).__name__
            if inner == "GRU":
                b = np.asarray(bias)
                d = {"Wx": jnp.asarray(kernel), "Wh": jnp.asarray(rec)}
                if b.ndim == 2:      # reset_after=True: [2, 3H]
                    d["b_in"] = jnp.asarray(b[0])
                    d["b_rec"] = jnp.asarray(b[1])
                else:
                    d["b_in"] = jnp.asarray(b)
                return d
            return {"Wx": jnp.asarray(kernel), "Wh": jnp.asarray(rec),
                    "b": jnp.asarray(bias)}

        p["fwd"] = _dir(w[0], w[1], w[2])
        p["bwd"] = _dir(w[3], w[4], w[5])
    elif t == "GRU":
        p["Wx"] = jnp.asarray(w[0])
        p["Wh"] = jnp.asarray(w[1])
        if len(w) > 2:
            b = np.asarray(w[2])
            if b.ndim == 2:          # reset_after=True: [2, 3H] (input, rec)
                p["b_in"] = jnp.asarray(b[0])
                p["b_rec"] = jnp.asarray(b[1])
            else:                     # reset_after=False: single [3H]
                p["b_in"] = jnp.asarray(b)
    elif t in ("LSTM", "SimpleRnn"):
        p["Wx"] = jnp.asarray(w[0])
        p["Wh"] = jnp.asarray(w[1])
        if len(w) > 2:
            p["b"] = jnp.asarray(w[2])
    elif t == "EmbeddingSequence":
        p["W"] = jnp.asarray(w[0])
    elif w:
        raise UnsupportedKerasConfigurationError(
            f"no weight mapping for layer type {t}"
        )
    return p, s


# ---------------------------------------------------------------------------
# HDF5 reading
# ---------------------------------------------------------------------------


def _read_h5(path: str):
    try:
        import h5py
    except ImportError as e:  # pragma: no cover - h5py is in the image
        raise UnsupportedKerasConfigurationError(
            "h5py is required for HDF5 import"
        ) from e
    return h5py.File(path, "r")


def _model_config_from_h5(f) -> dict:
    raw = f.attrs.get("model_config")
    if raw is None:
        raise InvalidKerasConfigurationError("no model_config attribute in HDF5")
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8")
    return json.loads(raw)


def _layer_weights_from_h5(f) -> Dict[str, List[np.ndarray]]:
    """{layer_name: [arrays in keras weight_names order]}."""
    grp = f["model_weights"] if "model_weights" in f else f
    out: Dict[str, List[np.ndarray]] = {}
    for lname in grp.attrs.get("layer_names", list(grp.keys())):
        if isinstance(lname, bytes):
            lname = lname.decode("utf-8")
        g = grp[lname]
        wnames = g.attrs.get("weight_names", [])
        arrays = []
        for wn in wnames:
            if isinstance(wn, bytes):
                wn = wn.decode("utf-8")
            arrays.append(np.asarray(g[wn]))
        if arrays:
            out[lname] = arrays
    return out


# ---------------------------------------------------------------------------
# model assembly
# ---------------------------------------------------------------------------


def _sequential_from_config(model_config: dict) -> Tuple[MultiLayerConfiguration, List[Optional[str]]]:
    """Build a MultiLayerConfiguration; returns (conf, keras layer name per
    OUR layer index) for weight pairing."""
    layers_cfg = model_config["config"]
    if isinstance(layers_cfg, dict):
        layers_cfg = layers_cfg.get("layers", [])
    if not layers_cfg:
        raise InvalidKerasConfigurationError("empty Sequential config")

    first = layers_cfg[0]
    shape = first["config"].get("batch_input_shape") or first["config"].get("batch_shape")
    if shape is None:
        raise InvalidKerasConfigurationError("first layer lacks batch_input_shape")
    first_real = next(
        lc["class_name"] for lc in layers_cfg if lc["class_name"] != "InputLayer"
    )
    input_type = _keras_input_type(shape, first_real)

    # a net is recurrent at the output if the LAST rnn layer returns sequences
    def _returns_seq(lc):
        if lc["class_name"] in _RETURNS_SEQUENCES:
            return lc["config"].get("return_sequences")
        if lc["class_name"] == "Bidirectional":
            return lc["config"].get("layer", {}).get("config", {}).get(
                "return_sequences")
        return False

    recurrent_out = any(_returns_seq(lc) for lc in layers_cfg[-3:])

    our_layers: List = []
    names: List[Optional[str]] = []
    _structural = ("InputLayer", "Flatten", "Dropout", "Activation",
                   "LeakyReLU", "ELU", "ThresholdedReLU", "PReLU",
                   "Cropping2D", "Permute", "RepeatVector",
                   "GaussianNoise", "GaussianDropout", "AlphaDropout",
                   "Masking", "Reshape", "SpatialDropout1D",
                   "SpatialDropout2D", "ZeroPadding1D", "ZeroPadding2D",
                   "Cropping1D", "UpSampling1D", "UpSampling2D")
    last_idx = max(
        i for i, lc in enumerate(layers_cfg)
        if lc["class_name"] not in _structural
    )
    cur_it = input_type
    pending_mask: Optional[float] = None
    mask_consumed = False
    _rnn_classes = set(_RETURNS_SEQUENCES) | {"Bidirectional"}
    # rnn_later[i]: does any layer AFTER index i still need the mask?
    rnn_later = [False] * (len(layers_cfg) + 1)
    for k in range(len(layers_cfg) - 1, -1, -1):
        rnn_later[k] = rnn_later[k + 1] or (
            layers_cfg[k]["class_name"] in _rnn_classes)
    # inference-identity layers keep zeros zero, so the derived mask survives
    _mask_transparent = ("Dropout", "SpatialDropout1D", "SpatialDropout2D",
                         "GaussianNoise", "GaussianDropout", "AlphaDropout")
    for i, lc in enumerate(layers_cfg):
        cn = lc["class_name"]
        cfg = lc.get("config", {})
        if cn == "Masking":
            # defer: the next recurrent layer is wrapped in MaskZero so the
            # mask is derived from its input (recurrent/MaskZeroLayer.java)
            pending_mask = float(cfg.get("mask_value", 0.0))
            mask_consumed = False  # a NEW mask must find its own consumer
            continue
        if cn == "Flatten" and cur_it.kind == "recurrent":
            # our Dense consumes [B,T,F] natively, so no auto-preprocessor
            # flattens timesteps — honor Keras's explicit Flatten with a
            # Reshape to [B, T*F]
            from deeplearning4j_tpu.nn.preprocessors import Reshape

            t = cur_it.timesteps or 1
            conv = Reshape(shape=(int(t * cur_it.size),))
            our_layers.append(conv)
            # no names entry: the weight-pairing loop skips preprocessor-
            # module layers without consuming a name
            cur_it = conv.output_type(cur_it)
            continue
        conv = _convert_layer(cn, cfg, as_output=(i == last_idx and cn == "Dense"),
                              recurrent=recurrent_out)
        if conv is None:
            continue
        if cn in _RETURNS_SEQUENCES and not cfg.get("return_sequences", False):
            # our recurrent layers return full sequences; Keras
            # return_sequences=False keeps only the final step
            from deeplearning4j_tpu.nn.layers import LastTimeStep

            conv = LastTimeStep(rnn=conv)
        if pending_mask is not None and (
                cn in _RETURNS_SEQUENCES or cn == "Bidirectional"):
            # MaskZero OUTERMOST: it derives the mask from its own input and
            # passes it down, so LastTimeStep picks the last VALID step.
            # Keras propagates the mask through EVERY downstream RNN, so the
            # wrap repeats for stacked RNNs — later layers re-derive it from
            # the zeros our masked steps emit (mask_value 0.0, not the
            # user's original value, which only applies to the raw input).
            from deeplearning4j_tpu.nn.layers import MaskZero

            conv = MaskZero(rnn=conv, mask_value=pending_mask)
            pending_mask = 0.0
            mask_consumed = True
        elif (pending_mask is not None and rnn_later[i + 1]
                and cn not in _mask_transparent):
            # a value-transforming layer between Masking and a later RNN
            # breaks mask derivation (padded steps stop being mask_value /
            # zero) — refuse rather than silently diverge from Keras
            raise UnsupportedKerasConfigurationError(
                f"Masking followed by {cn!r} before an RNN: the derived "
                "mask cannot survive a value-transforming layer")
        our_layers.append(conv)
        if type(conv).__module__.endswith("preprocessors"):
            # preprocessor-module results (e.g. Keras Reshape) carry no
            # weights; the pairing loop skips them without consuming a name
            cur_it = conv.output_type(cur_it)
            continue
        names.append(cfg.get("name", lc.get("name")))
        try:
            cur_it = conv.output_type(cur_it)
        except Exception:
            pass  # shape tracking is best-effort; MLN resolution re-derives
    if pending_mask is not None and not mask_consumed:
        # Keras silently lets a mask die at a non-mask-consuming layer
        # (e.g. Masking->Dense); we import the layers but the masking is a
        # no-op — surface that instead of dropping it silently
        import warnings

        warnings.warn(
            "Keras Masking layer has no downstream RNN consumer — the mask "
            "is dropped (padded steps are treated as real values)",
            stacklevel=2)
    conf = MultiLayerConfiguration(layers=tuple(our_layers), input_type=input_type)
    return conf, names


class KerasModelImport:
    """Entry points (KerasModelImport.java:50-121)."""

    # -- Sequential --------------------------------------------------------
    @staticmethod
    def import_keras_sequential_configuration(model_json: str) -> MultiLayerConfiguration:
        """From a model-config JSON string (importKerasSequentialConfiguration)."""
        conf, _ = _sequential_from_config(json.loads(model_json))
        return conf

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str) -> MultiLayerNetwork:
        with _read_h5(path) as f:
            model_config = _model_config_from_h5(f)
            if model_config.get("class_name") != "Sequential":
                raise InvalidKerasConfigurationError(
                    f"not a Sequential model: {model_config.get('class_name')}"
                )
            weights = _layer_weights_from_h5(f)
        conf, names = _sequential_from_config(model_config)
        model = MultiLayerNetwork(conf).init()
        new_params = list(model.params)
        new_state = list(model.state)
        # model.layers = conf.layers with auto-inserted preprocessors
        # interleaved; pair conf-layer names positionally, skipping inserted
        # preprocessor layers (they live in nn.preprocessors)
        j = 0
        for i, layer in enumerate(model.layers):
            if type(layer).__module__.endswith("preprocessors"):
                continue
            name = names[j]
            j += 1
            # Wrapper layers (LastTimeStep, MaskZero, ...) delegate init to
            # the wrapped rnn, so their params dict IS the innermost layer's
            # — walk the chain and map weights against the inner conf
            target = layer
            while type(target).__name__ in (
                    "LastTimeStep", "BidirectionalLastTimeStep", "MaskZero"):
                target = target.rnn
            if name in weights:
                new_params[i], new_state[i] = _set_weights(
                    target, weights[name], new_params[i], new_state[i]
                )
        model.params = tuple(new_params)
        model.state = tuple(new_state)
        return model

    # -- functional Model --------------------------------------------------
    @staticmethod
    def import_keras_model_and_weights(path: str) -> ComputationGraph:
        with _read_h5(path) as f:
            model_config = _model_config_from_h5(f)
            if model_config.get("class_name") == "Sequential":
                raise InvalidKerasConfigurationError(
                    "Sequential model: use import_keras_sequential_model_and_weights"
                )
            weights = _layer_weights_from_h5(f)
        conf, names = _graph_from_config(model_config)
        model = ComputationGraph(conf).init()
        _apply_graph_weights(model, names, weights)
        return model

    # -- auto-detect (ModelGuesser-ish surface) ---------------------------
    @staticmethod
    def import_keras_model(path: str):
        """Auto-detect Sequential vs functional (KerasModelImport's combined
        entry): returns MultiLayerNetwork or ComputationGraph."""
        with _read_h5(path) as f:
            kind = _model_config_from_h5(f).get("class_name")
        if kind == "Sequential":
            return KerasModelImport.import_keras_sequential_model_and_weights(path)
        return KerasModelImport.import_keras_model_and_weights(path)


# ---------------------------------------------------------------------------
# functional-API graphs
# ---------------------------------------------------------------------------

_MERGE_LAYERS = {
    "Add": ElementWiseVertex(op="add"),
    "Subtract": ElementWiseVertex(op="subtract"),
    "Multiply": ElementWiseVertex(op="product"),
    "Average": ElementWiseVertex(op="average"),
    "Maximum": ElementWiseVertex(op="max"),
    "Concatenate": MergeVertex(),
}


def _collect_history(obj, out: List[str]) -> None:
    """Recursively pull keras_history source names out of keras-3 node args."""
    if isinstance(obj, dict):
        hist = obj.get("keras_history")
        if hist:
            out.append(str(hist[0]))
            return
        for v in obj.values():
            _collect_history(v, out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _collect_history(v, out)


def _inbound_names(lc: dict) -> List[str]:
    """Inbound layer names from either format: Keras 2's nested lists
    ([[['name', 0, 0, {}], ...]]) or Keras 3's node dicts
    ([{'args': [<keras_tensor with keras_history>], ...}])."""
    nodes = lc.get("inbound_nodes", [])
    if not nodes:
        return []
    first = nodes[0]
    out: List[str] = []
    if isinstance(first, dict):  # keras 3
        _collect_history(first.get("args", []), out)
        return out
    for entry in first:
        if isinstance(entry, (list, tuple)):
            out.append(str(entry[0]))
    return out


def _graph_from_config(model_config: dict):
    cfg = model_config["config"]
    layers_cfg = cfg["layers"]
    builder = ComputationGraphConfiguration.builder()

    def _endpoint_names(spec) -> List[str]:
        # keras 2: [['name', 0, 0], ...]; keras 3 single endpoint: ['name', 0, 0]
        if not spec:
            return []
        if isinstance(spec[0], str):
            return [str(spec[0])]
        return [str(n[0]) for n in spec]

    input_names = _endpoint_names(cfg.get("input_layers", []))
    output_names = _endpoint_names(cfg.get("output_layers", []))
    if not input_names:
        raise InvalidKerasConfigurationError("functional model without input_layers")

    input_types = []
    by_name = {lc["config"].get("name", lc.get("name")): lc for lc in layers_cfg}
    for iname in input_names:
        lc = by_name[iname]
        shape = lc["config"].get("batch_input_shape") or lc["config"].get("batch_shape")
        # first consumer decides ambiguous ranks
        consumer = next(
            (l["class_name"] for l in layers_cfg if iname in _inbound_names(l)),
            "Dense",
        )
        input_types.append(_keras_input_type(shape, consumer))
    builder.add_inputs(*input_names)
    builder.set_input_types(*input_types)

    names: List[Tuple[str, Any]] = []  # (keras name, our layer conf) for weights
    for lc in layers_cfg:
        cn = lc["class_name"]
        name = lc["config"].get("name", lc.get("name"))
        if cn == "InputLayer":
            continue
        inbound = _inbound_names(lc)
        if cn in _MERGE_LAYERS:
            builder.add_vertex(name, _MERGE_LAYERS[cn], *inbound)
            continue
        if cn == "Flatten":
            # preprocessor insertion handles conv->ff; pass through vertex-free
            # by aliasing: downstream layers reference this name, so emit an
            # identity activation layer
            builder.add_layer(name, ActivationLayer(activation="identity"), *inbound)
            continue
        conv = _convert_layer(cn, lc.get("config", {}),
                              as_output=(name in output_names and cn == "Dense"))
        if cn in _RETURNS_SEQUENCES and not lc["config"].get("return_sequences", False):
            from deeplearning4j_tpu.nn.layers import LastTimeStep

            conv = LastTimeStep(rnn=conv)
        builder.add_layer(name, conv, *inbound)
        names.append((name, conv))
    builder.set_outputs(*output_names)
    return builder.build(), names


def _apply_graph_weights(model: ComputationGraph, names, weights) -> None:
    for kname, conv in names:
        if kname not in weights:
            continue
        p = model.params.get(kname) if isinstance(model.params, dict) else None
        st = model.state.get(kname) if isinstance(model.state, dict) else None
        if p is None:
            continue
        target = conv.rnn if type(conv).__name__ == "LastTimeStep" else conv
        new_p, new_s = _set_weights(target, weights[kname], p, st)
        model.params[kname] = new_p
        if isinstance(model.state, dict) and new_s is not None:
            model.state[kname] = new_s
