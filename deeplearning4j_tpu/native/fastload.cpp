// Native data-loading kernels for the TPU framework's host-side ETL.
//
// Role parity: the reference's ingestion path is native too (DataVec readers
// backed by javacpp/opencv; libnd4j does the array assembly). Here the
// accelerator math is XLA's job, but the host-side record parsing that
// feeds device buffers is a real bottleneck for big CSV/idx corpora —
// a single-pass C++ parser is ~20x the Python csv module.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Built automatically by native/__init__.py:
//   g++ -O3 -shared -fPIC -std=c++17 fastload.cpp -o fastload.so.bin
// (the .so.bin suffix keeps pkgutil from importing the artifact as a
// CPython extension module)

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse a numeric-only delimited buffer into row-major float64.
//   buf/n        : text buffer (need not be NUL-terminated)
//   skip_lines   : header lines to drop
//   delim        : field delimiter
//   out/max_vals : caller-allocated output and its capacity
//   n_rows/n_cols: parsed shape (every row must match n_cols)
// Returns 0 ok; 1 output capacity exceeded; 2 ragged rows; 3 bad/empty
// number (includes trailing-delimiter rows, matching the Python path's
// float('') error); 4 field too long for the fixed parse buffer.
int parse_csv_f64(const char* buf, int64_t n, int32_t skip_lines, char delim,
                  double* out, int64_t max_vals,
                  int64_t* n_rows, int64_t* n_cols) {
    int64_t i = 0;
    for (int32_t s = 0; s < skip_lines && i < n; ++s) {
        while (i < n && buf[i] != '\n') ++i;
        if (i < n) ++i;
    }
    int64_t rows = 0, cols = -1, vals = 0;
    while (i < n) {
        // skip blank lines
        if (buf[i] == '\n' || buf[i] == '\r') { ++i; continue; }
        int64_t row_cols = 0;
        bool expect_field = true;
        while (expect_field) {
            char tmp[64];
            int64_t t = 0;
            while (i < n && buf[i] != delim && buf[i] != '\n' && buf[i] != '\r') {
                if (t >= 63) return 4;  // refuse, never truncate silently
                tmp[t++] = buf[i];
                ++i;
            }
            if (t == 0) return 3;  // empty field ("1,2," or "1,,2")
            tmp[t] = '\0';
            char* end = nullptr;
            double v = strtod(tmp, &end);
            if (end == tmp || *end != '\0') return 3;
            if (vals >= max_vals) return 1;
            out[vals++] = v;
            ++row_cols;
            if (i < n && buf[i] == delim) {
                ++i;               // another field MUST follow
                expect_field = true;
            } else {
                expect_field = false;
            }
            while (i < n && buf[i] == '\r') ++i;
        }
        if (i < n && buf[i] == '\n') ++i;
        if (cols < 0) cols = row_cols;
        else if (row_cols != cols) return 2;
        ++rows;
    }
    *n_rows = rows;
    *n_cols = cols < 0 ? 0 : cols;
    return 0;
}

// Decode big-endian IDX (MNIST-style) image archives: u8 payload copied out,
// header validated. Returns 0 ok; 1 bad magic; 2 capacity exceeded.
int parse_idx_images(const uint8_t* buf, int64_t n,
                     uint8_t* out, int64_t max_bytes,
                     int64_t* count, int64_t* h, int64_t* w) {
    if (n < 16) return 1;
    uint32_t magic = (uint32_t(buf[0]) << 24) | (uint32_t(buf[1]) << 16) |
                     (uint32_t(buf[2]) << 8) | uint32_t(buf[3]);
    if (magic != 0x00000803u) return 1;
    auto be32 = [&](int64_t off) {
        return (int64_t(buf[off]) << 24) | (int64_t(buf[off + 1]) << 16) |
               (int64_t(buf[off + 2]) << 8) | int64_t(buf[off + 3]);
    };
    int64_t cnt = be32(4), hh = be32(8), ww = be32(12);
    if (cnt < 0 || hh < 0 || ww < 0) return 2;
    int64_t need = 0;
    // overflow-checked product: a corrupt header must not wrap negative and
    // slip past the bounds checks into memcpy
    if (__builtin_mul_overflow(cnt, hh, &need) ||
        __builtin_mul_overflow(need, ww, &need)) return 2;
    if (need > max_bytes || need > n - 16) return 2;
    memcpy(out, buf + 16, size_t(need));
    *count = cnt; *h = hh; *w = ww;
    return 0;
}

}  // extern "C"
