"""Native host-side data-loading kernels (C++ via ctypes).

The compute path is XLA; this package covers the RUNTIME side the reference
also keeps native (DataVec's javacpp readers): single-pass CSV and IDX
parsers compiled from ``fastload.cpp`` with the system g++ on first use and
cached next to the source. Everything degrades gracefully: if no compiler
is available the callers fall back to the pure-Python paths, so the
framework never hard-requires a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastload.cpp")
# .so.bin, NOT .so: pkgutil.walk_packages would otherwise try to import the
# artifact as a CPython extension module (ctypes loads any filename)
_LIB = os.path.join(_HERE, "fastload.so.bin")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    if os.path.exists(_LIB):
        try:
            fresh = os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        except OSError:
            fresh = True  # source missing (binary-only deploy): use the cache
        if fresh:
            return _LIB
    if not os.path.exists(_SRC):
        return None
    # compile to a process-unique temp path and os.replace (atomic) so
    # concurrent builders (e.g. jax.distributed workers) never load a
    # half-written .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return _LIB
    except (OSError, subprocess.SubprocessError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled library, compiled on first call; None when no toolchain
    is available or the cached .so fails to load (callers must fall back)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.parse_csv_f64.restype = ctypes.c_int
        lib.parse_csv_f64.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_char,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.parse_idx_images.restype = ctypes.c_int
        lib.parse_idx_images.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def parse_csv(data: bytes, *, skip_lines: int = 0,
              delimiter: str = ",") -> Optional[np.ndarray]:
    """Numeric CSV bytes -> [rows, cols] float64, or None if the native lib
    is unavailable. Raises ValueError on malformed input (ragged rows,
    non-numeric fields) — same contract as the Python path."""
    lib = get_lib()
    if lib is None:
        return None
    # capacity: every field needs >= 2 bytes ("x,"), so len/2+1 bounds it
    max_vals = len(data) // 2 + 2
    out = np.empty(max_vals, np.float64)
    n_rows = ctypes.c_int64(0)
    n_cols = ctypes.c_int64(0)
    rc = lib.parse_csv_f64(
        data, len(data), skip_lines, delimiter.encode()[0:1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_vals,
        ctypes.byref(n_rows), ctypes.byref(n_cols))
    if rc == 2:
        raise ValueError("native CSV parse: ragged rows")
    if rc == 3:
        raise ValueError("native CSV parse: non-numeric or empty field")
    if rc == 4:
        raise ValueError("native CSV parse: field too long")
    if rc != 0:
        raise ValueError(f"native CSV parse failed (code {rc})")
    r, c = n_rows.value, n_cols.value
    return out[:r * c].reshape(r, c).copy()


def parse_idx_images(data: bytes) -> Optional[np.ndarray]:
    """IDX image archive bytes -> [n, h, w] uint8, or None if unavailable.
    Raises ValueError on a bad magic/truncated payload."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    out = np.empty(max(len(data) - 16, 1), np.uint8)
    cnt = ctypes.c_int64(0)
    h = ctypes.c_int64(0)
    w = ctypes.c_int64(0)
    rc = lib.parse_idx_images(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), out.size,
        ctypes.byref(cnt), ctypes.byref(h), ctypes.byref(w))
    if rc != 0:
        raise ValueError(f"native IDX parse failed (code {rc})")
    n, hh, ww = cnt.value, h.value, w.value
    return out[:n * hh * ww].reshape(n, hh, ww).copy()
