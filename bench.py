"""Benchmark: LeNet-5 MNIST-shape training throughput (BASELINE config #1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md): vs_baseline is measured
against a fixed nominal reference of 10,000 samples/sec — roughly what the
reference's LeNet-5 sustains on a V100 via nd4j-cuda — so the ratio is
meaningful across rounds even though the true baseline must be measured.
"""

from __future__ import annotations

import json
import time

import numpy as np

NOMINAL_BASELINE_SAMPLES_PER_SEC = 10_000.0


def main():
    import jax
    from deeplearning4j_tpu.models import LeNet5
    from deeplearning4j_tpu.nn.model import MultiLayerNetwork

    batch = 256
    rs = np.random.RandomState(0)
    x = rs.rand(batch, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch)]

    import jax.numpy as jnp

    model = MultiLayerNetwork(LeNet5(dtype="float32")).init()

    # Drive the raw jitted step (no per-step host sync on the loss — the
    # listener path would serialize host<->device every iteration).
    step = model._get_step_fn(False)
    xd, yd = jnp.asarray(x), jnp.asarray(y)
    params, opt, state = model.params, model.opt_state, model.state
    rng = jax.random.PRNGKey(0)

    def run(n, params, opt, state):
        for i in range(n):
            params, opt, state, _, loss = step(
                params, opt, state, jnp.asarray(i, jnp.int32), rng, xd, yd, None, None, ()
            )
        jax.block_until_ready(loss)
        return params, opt, state

    params, opt, state = run(5, params, opt, state)  # warmup/compile
    steps = 50
    t0 = time.perf_counter()
    params, opt, state = run(steps, params, opt, state)
    dt = time.perf_counter() - t0

    samples_per_sec = steps * batch / dt
    print(json.dumps({
        "metric": "lenet5_mnist_train_throughput",
        "value": round(samples_per_sec, 1),
        "unit": "samples/sec",
        "vs_baseline": round(samples_per_sec / NOMINAL_BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
