"""Benchmarks for the BASELINE.md configs, run on the real chip.

Emits one JSON line per sub-benchmark as it completes, then ONE final JSON
line ``{"metric", "value", "unit", "vs_baseline", "extras": [...]}`` whose
headline is ResNet50 images/sec (BASELINE config #2, the north-star metric)
and whose ``extras`` array carries every measured metric, including MFU.

Covered (BASELINE.md "Baselines to measure"):
  #1 LeNet-5 MNIST MultiLayerNetwork            -> samples/sec
  #2 zoo ResNet50 ComputationGraph @ 224^2      -> images/sec + analytic MFU
  #3 GravesLSTM char-RNN (TextGenerationLSTM)   -> tokens/sec + analytic MFU
  #5 Word2Vec skip-gram negative sampling       -> pairs/sec
(#4, multi-device ResNet50, needs >1 chip; the driver validates the sharded
path separately via __graft_entry__.dryrun_multichip.)

The reference publishes no numbers (BASELINE.md), so each ``vs_baseline`` is
measured against a documented NOMINAL estimate of what the reference's
nd4j-cuda path sustains on a V100 — a fixed yardstick that keeps the ratio
comparable across rounds until a true baseline is measured:
  LeNet-5    10,000 samples/sec  (r01/r02 yardstick, unchanged)
  ResNet50      360 images/sec   (public V100 fp32 ResNet50 training rate;
                                  the reference's cuDNN path is at best this)
  char-RNN  100,000 tokens/sec   (cuDNN LSTM 2x256, T=50, V100-class)
  Word2Vec  500,000 pairs/sec    (SkipGram.java on a fast multicore host)

MFU conventions: ResNet50 uses ANALYTIC train FLOPs (2*MACs forward, x3 for
fwd+bwd) so the number is comparable to published MFU figures; the LSTM
bench instead uses XLA's own cost analysis of the compiled step (after
fusion the analytic x3 overcounts what executes) against the bf16 roofline
(jax's default TPU matmul precision multiplies f32 inputs in bf16). Peak is
looked up from the device kind.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# BENCH_SMOKE=1: tiny shapes + few steps, for CPU validation of the harness
# itself (tests / local runs). Real numbers come from the default config.
SMOKE = bool(int(os.environ.get("BENCH_SMOKE", "0")))

# DL4J_TPU_BENCH_BUDGET_S: per-metric wall-clock budget (seconds). Round 5's
# lenet5 run timed out at the subprocess kill (rc=124, no JSON) because the
# dispatch-latency microbench repeats 5 timing loops plus a chained variant
# with no notion of elapsed time. Now every bench arms a deadline at entry:
# _timed() shrinks its measure loop to fit the time remaining and optional
# variants (lenet5's chained arm, extra median reps) are skipped once the
# budget is spent — a full `python bench.py` always emits JSON for every
# metric. 0 disables the budget.
_BUDGET_S = float(os.environ.get("DL4J_TPU_BENCH_BUDGET_S", "120"))
_DEADLINE: float | None = None


def _budget_start():
    global _DEADLINE
    _DEADLINE = (time.perf_counter() + _BUDGET_S) if _BUDGET_S > 0 else None


def _budget_left() -> float:
    if _DEADLINE is None:
        return float("inf")
    return _DEADLINE - time.perf_counter()

NOMINAL = {
    "lenet5_mnist_train_throughput": 10_000.0,
    "resnet50_224_train_throughput": 360.0,
    "lstm_char_rnn_train_throughput": 100_000.0,
    "word2vec_skipgram_throughput": 500_000.0,
}

def _peak_flops(dtype: str) -> float | None:
    # single source of truth for the per-backend roofline (absorbed the
    # table this file carried since PR 3): obs/profile.py
    from deeplearning4j_tpu.obs import profile

    return profile.peak_flops(dtype)


def _mfu_from_cost(compiled, steps_per_sec: float) -> dict:
    """MFU from XLA's own cost analysis of an AOT-compiled step against the
    bf16 roofline (jax's default TPU matmul precision multiplies f32 inputs
    in bf16). Harvests through obs.profile so the same numbers land in the
    cost gauges. Returns {} when unavailable."""
    from deeplearning4j_tpu.obs import profile

    peak = _peak_flops("bfloat16")
    entry = profile.harvest_compiled("bench.step", compiled, key="bench")
    if not peak or not entry or not entry.get("flops"):
        return {}
    return {"mfu": round(entry["flops"] * steps_per_sec / peak, 4),
            "xla_gflops_per_step": round(entry["flops"] / 1e9, 2)}


def _timed(run, warmup_steps: int = 5, steps: int = 30):
    """run(n) executes n steps and blocks on the result. Returns (sec, steps).

    Budget-aware: the timed warmup yields a per-step estimate, and the
    measure loop is clamped so warmup + measure fit the bench's remaining
    DL4J_TPU_BENCH_BUDGET_S (never below 1 step — a shrunk-but-measured
    number beats a killed subprocess with no JSON). The PRE-FLIGHT check
    matters as much as the clamp: first-compile time counts against the
    budget too, so a call that starts past the deadline collapses to the
    1-warmup/1-step minimum instead of running its full warmup (round 5's
    lenet5 rc=124 was five full reps stacked after a long compile, each
    only checking the budget on the way OUT)."""
    if SMOKE:
        warmup_steps, steps = 1, 2
    if _budget_left() <= 0:
        warmup_steps, steps = 1, 1
    t0 = time.perf_counter()
    run(warmup_steps)
    per_step = (time.perf_counter() - t0) / max(warmup_steps, 1)
    left = _budget_left()
    if left != float("inf") and per_step > 0:
        steps = max(1, min(steps, int(left / per_step)))
    t0 = time.perf_counter()
    run(steps)
    return time.perf_counter() - t0, steps


# ---------------------------------------------------------------------------
# Analytic FLOPs
# ---------------------------------------------------------------------------

def _graph_fwd_flops_per_example(cg) -> float:
    """2*MACs of the conv/dense compute in one forward pass of one example,
    walked from the resolved ComputationGraph shapes."""
    from deeplearning4j_tpu.nn.layers.convolution import (
        Conv2D, DepthwiseConv2D, SeparableConv2D)

    total = 0.0
    for name in cg.topo_order:
        v = cg.rt[name]
        if not v.spec.is_layer():
            continue
        cfg, it = v.config, v.input_types[0]
        ot = cg.vertex_types[name]
        if isinstance(cfg, SeparableConv2D):
            kh, kw = cfg.kernel
            mid = it.channels * cfg.depth_multiplier
            total += 2.0 * ot.height * ot.width * mid * kh * kw   # depthwise
            total += 2.0 * ot.height * ot.width * ot.channels * mid  # pointwise
        elif isinstance(cfg, DepthwiseConv2D):
            kh, kw = cfg.kernel
            total += 2.0 * ot.height * ot.width * ot.channels * kh * kw
        elif type(cfg) is Conv2D:
            kh, kw = cfg.kernel
            total += 2.0 * ot.height * ot.width * ot.channels * kh * kw * it.channels
        elif type(cfg).__name__ in ("Dense", "OutputLayer"):
            total += 2.0 * it.flat_size() * cfg.n_out
    return total


# ---------------------------------------------------------------------------
# Benchmarks
# ---------------------------------------------------------------------------

def bench_lenet5():
    """BASELINE #1 — LeNet-5 MNIST-shape training throughput."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import LeNet5
    from deeplearning4j_tpu.nn.model import MultiLayerNetwork

    batch = 256
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, 28, 28, 1).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch)])

    model = MultiLayerNetwork(LeNet5(dtype="float32")).init()
    step = model._get_step_fn(False)
    st = [model.params, model.opt_state, model.state]
    rng = jax.random.PRNGKey(0)

    def run(n):
        loss = None
        for i in range(n):
            st[0], st[1], st[2], _, loss = step(
                st[0], st[1], st[2], jnp.asarray(i, jnp.int32), rng, x, y,
                None, None, ())
        float(loss)  # value fetch: the only sync the tunnel cannot elide

    # dispatch-latency-bound microbench: single draws vary with tunnel
    # jitter, so report the median of k timing loops with the spread —
    # stopping early (with at least one draw) once the budget is spent
    reps = []
    k = 1 if SMOKE else 5
    for _ in range(k):
        # pre-flight: the deadline is checked BEFORE committing to another
        # rep (compiles/warmup count against the budget), not only after
        if reps and _budget_left() <= 0:
            break
        dt, steps = _timed(run, warmup_steps=5, steps=50)
        reps.append(steps * batch / dt)
    reps.sort()
    per_step = reps[len(reps) // 2]

    # ROUND 5: fit()'s chained hot loop — K steps per dispatch (lax.scan
    # of the step body) amortizes the ~4 ms per-dispatch floor that
    # dominates this small model (docs/PERF.md LeNet). The chained arm
    # costs a SECOND full compile, so it is the first thing the budget
    # drops (round 5's rc=124: this compile + 5 more timing loops blew
    # the 900 s subprocess kill with no JSON emitted at all).
    out = {
        "metric": "lenet5_mnist_train_throughput",
        "median_of": len(reps),
        "per_step_dispatch_samples_per_sec": round(per_step, 1),
    }
    if _budget_left() < max(10.0, 0.2 * _BUDGET_S):
        sps = per_step
        out["chained_skipped"] = "bench budget exceeded (DL4J_TPU_BENCH_BUDGET_S)"
    else:
        K = 2 if SMOKE else 10
        chain = model._get_chain_step()
        xs = jnp.stack([x] * K)
        ys = jnp.stack([y] * K)
        st2 = st  # model.params were DONATED by the per-step loop; st is live

        def run_chained(n):
            losses = None
            for i in range(n):
                st2[0], st2[1], st2[2], losses = chain(
                    st2[0], st2[1], st2[2], jnp.asarray(i * K, jnp.int32),
                    jax.random.PRNGKey(i), xs, ys)
            float(losses[-1])  # value fetch
        reps2 = []
        for _ in range(k):
            if reps2 and _budget_left() <= 0:
                break
            dt, disp = _timed(run_chained, warmup_steps=2, steps=10)
            reps2.append(disp * K * batch / dt)
        reps2.sort()
        sps = reps2[len(reps2) // 2]
        out["chain_steps_per_dispatch"] = K
        out["spread_samples_per_sec"] = [round(reps2[0], 1), round(reps2[-1], 1)]
    out.update({
        "value": round(sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(sps / NOMINAL["lenet5_mnist_train_throughput"], 3),
    })
    return out


def bench_resnet50():
    """BASELINE #2 — zoo ResNet50 @ 224x224, images/sec + analytic MFU.

    Measured MFU (v5e, b128, bf16, round 4): ~0.28 — proven to be the
    chip's ceiling for this op mix by the round-4 null experiment
    (tools/null_resnet50.py: a from-scratch no-framework JAX step measures
    0.288; full head-to-head in docs/PERF.md "Null experiment"). Levers
    that mattered: batch 64->128, BatchNorm folded to per-channel bf16
    scale/shift with the stable shifted-stats form (0.13 -> 0.26), and
    round 4's REMOVAL of the round-3 strided-1x1 slice-then-matmul rewrite
    (+12% then, -12% on the round-4 toolchain). The MLPerf-style
    stem="space_to_depth" variant adds ~+5% but changes parameter layout
    away from reference parity, so the faithful conv7 stem stays here."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo_graph import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    batch, classes, dtype = 128, 1000, "bfloat16"
    size = 224
    if SMOKE:
        batch, classes, size = 2, 10, 64
    cg = ComputationGraph(
        ResNet50(height=size, width=size, num_classes=classes, dtype=dtype)).init()
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, size, size, 3), jnp.bfloat16)
    y = jnp.asarray(np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)])

    def run(n):
        loss = None
        for _ in range(n):
            loss = cg.fit_batch((x, y))
        float(loss)  # value fetch: the only sync the tunnel cannot elide

    dt, steps = _timed(run, warmup_steps=3, steps=20)
    ips = steps * batch / dt
    fwd = _graph_fwd_flops_per_example(cg)
    out = {
        "metric": "resnet50_224_train_throughput",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": round(ips / NOMINAL["resnet50_224_train_throughput"], 3),
        "batch": batch,
        "dtype": dtype,
        "analytic_fwd_gflops_per_image": round(fwd / 1e9, 2),
    }
    peak = _peak_flops(dtype)
    if peak:
        out["mfu"] = round(3.0 * fwd * ips / peak, 4)
        out["peak_tflops"] = peak / 1e12

    # TPU-optimized stem variant (SpaceToDepth + 4x4/s1 — NOT the reference
    # layout; reported separately, labeled). Costs a second full compile, so
    # it is opt-in: BENCH_S2D=1 (measured result recorded in docs/PERF.md).
    if not SMOKE and os.environ.get("BENCH_S2D") == "1":
        cg2 = ComputationGraph(
            ResNet50(height=size, width=size, num_classes=classes,
                     dtype=dtype, stem="space_to_depth")).init()

        def run2(n):
            loss = None
            for _ in range(n):
                loss = cg2.fit_batch((x, y))
            float(loss)

        dt2, steps2 = _timed(run2, warmup_steps=3, steps=20)
        ips2 = steps2 * batch / dt2
        fwd2 = _graph_fwd_flops_per_example(cg2)  # the variant's OWN flops
        out["s2d_stem_variant_images_per_sec"] = round(ips2, 1)
        if peak:
            out["s2d_stem_variant_mfu"] = round(3.0 * fwd2 * ips2 / peak, 4)
    return out


def bench_lstm_char_rnn():
    """BASELINE #3 — GravesLSTM char-RNN (TextGenerationLSTM), tokens/sec.

    Round-3 history: hoisting the input projection out of the scan (one
    [B*T,I]x[I,4H] MXU matmul up front, only the recurrent [B,H]x[H,4H]
    inside the scan — nn/layers/recurrent.py ``_input_proj``) took this from
    1.85M to tens of millions of tokens/sec on v5e. MFU here is computed
    from XLA's OWN cost analysis of the compiled step (the analytic
    3x-forward formula overcounts what XLA actually executes after fusion,
    yielding nonsense >1 values at these speeds)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import TextGenerationLSTM
    from deeplearning4j_tpu.nn.model import MultiLayerNetwork

    vocab, timesteps, hidden, batch = 77, 50, 256, 128
    if SMOKE:
        hidden, batch = 32, 4
    rs = np.random.RandomState(0)
    ids = rs.randint(0, vocab, (batch, timesteps))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)])

    failed_arms = {}

    def measure(policy):
        """One arm (scan or the round-5 weight-stationary fused kernel);
        the env flag is read at trace time, so a fresh model+compile per
        arm suffices. Returns (tokens/sec, compiled) or None on failure
        (the fused kernel is new — the bench must not die with it)."""
        os.environ["DL4J_TPU_FUSED_LSTM"] = "1" if policy == "fused" else "0"
        try:
            model = MultiLayerNetwork(TextGenerationLSTM(
                vocab_size=vocab, timesteps=timesteps, hidden=hidden,
                dtype="float32")).init()
            step = model._get_step_fn(False)
            rng = jax.random.PRNGKey(0)
            compiled = step.lower(
                model.params, model.opt_state, model.state,
                jnp.asarray(0, jnp.int32), rng, x, y, None, None, ()).compile()
            st = [model.params, model.opt_state, model.state]

            def run(n):
                loss = None
                for i in range(n):
                    st[0], st[1], st[2], _, loss = compiled(
                        st[0], st[1], st[2], jnp.asarray(i, jnp.int32), rng,
                        x, y, None, None, ())
                float(loss)  # value fetch: the only reliable tunnel sync

            dt, steps = _timed(run, warmup_steps=5, steps=50)
            return steps * batch * timesteps / dt, compiled
        except Exception as e:  # pragma: no cover - hardware-dependent
            # recorded in the JSON result too — a broken fused kernel must
            # be visible in BENCH output, not just a stderr note
            failed_arms[policy] = f"{type(e).__name__}: {e}"[:200]
            print(f"# lstm arm {policy} failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return None

    old = os.environ.get("DL4J_TPU_FUSED_LSTM")
    try:
        scan_arm = measure("scan")
        fused_arm = measure("fused")
    finally:
        if old is None:
            os.environ.pop("DL4J_TPU_FUSED_LSTM", None)
        else:
            os.environ["DL4J_TPU_FUSED_LSTM"] = old
    arms = {k: v for k, v in (("scan", scan_arm), ("fused", fused_arm)) if v}
    if not arms:
        raise RuntimeError("both LSTM bench arms failed")
    best = max(arms, key=lambda k: arms[k][0])
    tps, compiled = arms[best]
    out = {
        "metric": "lstm_char_rnn_train_throughput",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / NOMINAL["lstm_char_rnn_train_throughput"], 3),
        "batch": batch,
        "timesteps": timesteps,
        "lstm_path": best,
        "arms_tokens_per_sec": {k: round(v[0], 1) for k, v in arms.items()},
    }
    if failed_arms:
        out["failed_arms"] = failed_arms
    out.update(_mfu_from_cost(compiled, tps / (batch * timesteps)))
    return out


def bench_word2vec():
    """BASELINE #5 — Word2Vec: fused-step pairs/sec AND end-to-end corpus
    tokens/sec (corpus -> vocab -> subsampled pairs -> device steps).

    ROUND-4 CORRECTION: rounds 1-3 reported ~3B pairs/sec for the fused
    step. That was a sync artifact (block_until_ready elided through the
    axon tunnel; a loss-value fetch is the only reliable sync — docs/PERF.md).
    The honest fused-step rate is ~4-5M pairs/sec, scatter-add bound; the
    earlier 'dispatch-bound below 16K pairs' batch guidance was derived
    from the phantom numbers and is superseded by the end-to-end split
    reported here.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nlp.embeddings import _sg_ns_step

    vocab_size, dim, batch, negative = 100_000, 100, 65536, 5
    if SMOKE:
        vocab_size, batch = 1000, 64
    rs = np.random.RandomState(0)
    params = {
        "syn0": jnp.asarray((rs.rand(vocab_size, dim).astype(np.float32) - 0.5) / dim),
        "syn1neg": jnp.zeros((vocab_size, dim), jnp.float32),
    }
    step = jax.jit(_sg_ns_step, donate_argnums=(0,))
    centers = jnp.asarray(rs.randint(0, vocab_size, batch, dtype=np.int32))
    contexts = jnp.asarray(rs.randint(0, vocab_size, batch, dtype=np.int32))
    negs = jnp.asarray(rs.randint(0, vocab_size, (batch, negative), dtype=np.int32))
    lr = jnp.asarray(0.025, jnp.float32)

    box = [params]

    def run(n):
        loss = None
        for _ in range(n):
            box[0], loss = step(box[0], centers, contexts, negs, lr)
        # ROUND-4 CORRECTION: a loss-VALUE fetch is the only sync the axon
        # tunnel cannot elide. block_until_ready here let ~50 queued steps
        # report as done, inflating rounds 1-3 to a phantom 2.95B pairs/sec;
        # the honest fused-step rate is ~4M pairs/sec (scatter-add bound).
        float(loss)

    dt, steps = _timed(run, warmup_steps=5, steps=50)
    pps = steps * batch / dt

    # ---- END-TO-END: corpus -> vocab -> subsampled pairs -> device steps.
    # The reference's bottleneck is exactly this host pipeline
    # (SequenceVectors.java:1021,1127 AsyncSequencer + per-pair threads);
    # here the host side is the vectorized numpy pair backend and device
    # dispatch is async, so pair-gen for batch k+1 overlaps the device
    # executing batch k (JAX's dispatch queue IS the double buffer).
    import time as _time

    from deeplearning4j_tpu.nlp.embeddings import (
        Word2Vec, _fast_pairs, subsample_probs)

    n_tokens, v_eff, sent_len = 2_000_000, 50_000, 1000
    if SMOKE:
        n_tokens, v_eff, sent_len = 20_000, 500, 100
    zipf = rs.zipf(1.3, n_tokens * 2)
    toks = zipf[zipf <= v_eff][:n_tokens].astype(np.int64)
    corpus = [[f"w{t}" for t in toks[i:i + sent_len]]
              for i in range(0, len(toks), sent_len)]

    m = Word2Vec(layer_size=dim, window=5, negative=negative,
                 min_word_frequency=1, epochs=1, seed=1,
                 batch_size=65536, pair_backend="numpy")
    t0 = _time.perf_counter()
    m.build_vocab(corpus)
    t_vocab = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    m.fit(corpus)          # cold: includes XLA compiles of scan + tail
    jax.block_until_ready(m.params["syn0"])
    t_fit_cold = _time.perf_counter() - t0
    idx_seqs = m._index_sequences(corpus)
    t0 = _time.perf_counter()
    m._run_epochs(idx_seqs, 1)   # warm steady-state epoch (the number that
    jax.block_until_ready(m.params["syn0"])  # amortizes over real training)
    t_epoch_warm = _time.perf_counter() - t0
    e2e_tps_cold = n_tokens / (t_vocab + t_fit_cold)
    e2e_tps = n_tokens / (t_vocab / 2 + t_epoch_warm)  # vocab amortized over 2 epochs

    # host-only pair generation (same generator, no device steps) to
    # quantify the host/device split
    keep = subsample_probs(m.vocab, m.sample)
    t0 = _time.perf_counter()
    n_pairs = sum(len(c) for c, _t in _fast_pairs(
        idx_seqs, m.window, keep, np.random.RandomState(1)))
    t_host = _time.perf_counter() - t0

    return {
        "metric": "word2vec_skipgram_throughput",
        "value": round(pps, 1),
        "unit": "pairs/sec",
        "vs_baseline": round(pps / NOMINAL["word2vec_skipgram_throughput"], 3),
        "vocab": vocab_size,
        "dim": dim,
        "end_to_end_tokens_per_sec": round(e2e_tps, 1),
        "end_to_end_tokens_per_sec_cold": round(e2e_tps_cold, 1),
        "end_to_end_corpus_tokens": n_tokens,
        "end_to_end_split_sec": {
            "vocab_build": round(t_vocab, 3),
            "first_epoch_incl_compile": round(t_fit_cold, 3),
            "warm_epoch": round(t_epoch_warm, 3),
            "host_pairgen_alone": round(t_host, 3),
        },
        # first_epoch_incl_compile is XLA-compile-dominated (~5x warm,
        # r4); a persistent cache makes later PROCESSES warm — record the
        # ACTIVE cache dir so the cold number stays interpretable (empty
        # env value = default dir, so read the live jax config, not env)
        "compile_cache_dir": jax.config.jax_compilation_cache_dir or None,
        "host_pairgen_pairs_per_sec": round(n_pairs / max(t_host, 1e-9), 1),
    }


def bench_transformer():
    """Beyond-reference: TransformerLM train step, tokens/sec at T=2048
    (flash-attention path on TPU — the reference has no attention at all;
    recorded so the flagship extension's speed is a tracked number)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.nn.model import MultiLayerNetwork

    # MXU-saturating config (round 4): d_model 2048 fills the 128x128
    # systolic array; the Pallas flash backward keeps attention blockwise
    # in both directions. Round-3 ran d512/B8 (MFU 0.125); this config
    # measures 0.47+ on the same chip.
    vocab, T, d_model, heads, blocks, batch = 2048, 2048, 2048, 16, 8, 16
    if SMOKE:
        vocab, T, d_model, heads, blocks, batch = 64, 32, 32, 2, 2, 2
    model = MultiLayerNetwork(TransformerLM(
        vocab_size=vocab, max_len=T, d_model=d_model, n_heads=heads,
        n_blocks=blocks, updater={"type": "adam", "lr": 1e-4})).init()
    rs = np.random.RandomState(0)
    ids = rs.randint(0, vocab, (batch, T))
    x = jnp.asarray(ids)
    # sparse integer labels (round 4): the [B,T,V] one-hot tensor was 268MB
    # of host->device traffic per compile at this config; same loss math
    # (tests/test_sparse_labels.py asserts bit-equivalence)
    y = jnp.asarray(np.roll(ids, -1, axis=1).astype(np.int32))

    step = model._get_step_fn(False)
    rng = jax.random.PRNGKey(0)
    compiled = step.lower(model.params, model.opt_state, model.state,
                          jnp.asarray(0, jnp.int32), rng, x, y,
                          None, None, ()).compile()
    st = [model.params, model.opt_state, model.state]

    def run(n):
        loss = None
        for i in range(n):
            st[0], st[1], st[2], _, loss = compiled(
                st[0], st[1], st[2], jnp.asarray(i, jnp.int32), rng, x, y,
                None, None, ())
        float(loss)  # value fetch: a hard sync the tunnel cannot elide
        # (block_until_ready alone under-measured this config ~10x)

    dt, steps = _timed(run, warmup_steps=3, steps=15)
    tps = steps * batch * T / dt
    out = {
        "metric": "transformer_lm_train_throughput",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "batch": batch,
        "seq_len": T,
        "d_model": d_model,
        "note": "beyond-reference flagship (flash-attention path)",
    }
    out.update(_mfu_from_cost(compiled, tps / (batch * T)))
    return out


def bench_serving_mixed():
    """Mixed-batch-size serving — the shape-bucketing tentpole's probe.

    Requests drawn from a fixed size list flow through ParallelInference
    batched mode; without bucketing every distinct coalesced batch size
    compiles a fresh inference executable, with it the ladder collapses
    them onto a handful of buckets. Reports WARM throughput (every bucket
    pre-touched) plus the observed trace/compile count and bucket-hit
    histogram from the utils.bucketing telemetry, so the trajectory tracks
    compile-count regressions alongside examples/sec."""
    from concurrent.futures import ThreadPoolExecutor

    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.utils import bucketing

    n_feat, hidden, classes = 32, 256, 10
    sizes = [1, 2, 3, 5, 7, 9, 12, 17, 21, 27]
    rounds = 8 if SMOKE else 50
    if SMOKE:
        hidden = 16
    conf = MultiLayerConfiguration(
        layers=(Dense(n_out=hidden, activation="relu"),
                OutputLayer(n_out=classes, activation="softmax")),
        input_type=InputType.feed_forward(n_feat),
        updater={"type": "sgd", "lr": 0.05},
    )
    model = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    reqs = [rs.rand(s, n_feat).astype(np.float32) for s in sizes]

    tel = bucketing.telemetry()
    tel.reset()
    max_bs = 64
    pi = ParallelInference(model, mode="batched", max_batch_size=max_bs)
    try:
        # warmup: touch every ladder rung up to the coalesce cap — the
        # worker merges queued requests, so a coalesced total can land on
        # any bucket <= max_batch_size, not just the per-request ones.
        # Pre-compiling every rung means the timed window adds ZERO traces.
        rungs, n = [], 1
        while n <= max_bs:
            b = min(bucketing.bucket_size(n), max_bs)
            rungs.append(b)
            n = b + 1
        for b in rungs:
            model.output(np.zeros((b, n_feat), np.float32))
        compiles_warm = tel.compiles("mln.output")
        with ThreadPoolExecutor(max_workers=8) as pool:
            t0 = time.perf_counter()
            futs = [pool.submit(pi.output, reqs[i % len(reqs)])
                    for i in range(rounds * len(sizes))]
            total = sum(len(f.result()) for f in futs)
            dt = time.perf_counter() - t0
    finally:
        pi.shutdown()
    snap = tel.snapshot()
    return {
        "metric": "serving_mixed_batch_throughput",
        "value": round(total / dt, 1),
        "unit": "examples/sec",
        "distinct_request_sizes": len(set(sizes)),
        "distinct_buckets": len(tel.buckets_used("pi.batched")),
        "buckets_warmed": len(set(rungs)),
        "observed_compiles": tel.compiles("mln.output"),
        "compiles_after_warmup": tel.compiles("mln.output") - compiles_warm,
        "bucket_hits": snap["bucket_hits"],
        "padded_examples": snap["padded_examples"],
        "real_examples": snap["real_examples"],
    }


def bench_serving_slo():
    """Serving-tier SLO bench — the serve/ continuous-batching scheduler
    under a closed-loop load generator.

    Three phases:
      ramp      concurrency sweep; each level hammers its own ModelWorker
                (fresh route -> clean quantiles) with mixed-size requests.
                Saturation = the level with the highest request rate.
      headline  p99 latency (ms) AT saturation, from the SLO tracker's
                dl4j_request_seconds P^2 quantiles — the same series the
                /metrics endpoint and burn-rate gauge are built on.
      overload  a deliberately starved worker (queue_limit=2) blasted by
                4x the saturation concurrency; gates that the scheduler
                SHEDS (dl4j_shed_total > 0) and the burn-rate gauge reacts
                rather than letting the queue grow without bound.

    Also gates the AOT contract end-to-end: after registry warm-up the
    entire load run must add ZERO compiles on the request path."""
    import threading
    from deeplearning4j_tpu import obs
    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.obs import slo
    from deeplearning4j_tpu.serve import (
        ModelRegistry, ModelWorker, ServeConfig, ShedError)
    from deeplearning4j_tpu.utils import bucketing

    n_feat, hidden, classes = 32, 256, 10
    max_batch = 32
    levels = [1, 2, 4, 8, 16]
    window_s = 1.0
    if SMOKE:
        hidden, max_batch = 16, 16
        levels = [1, 4]
        window_s = 0.25

    conf = MultiLayerConfiguration(
        layers=(Dense(n_out=hidden, activation="relu"),
                OutputLayer(n_out=classes, activation="softmax")),
        input_type=InputType.feed_forward(n_feat),
        updater={"type": "sgd", "lr": 0.05},
    )
    model = MultiLayerNetwork(conf).init()
    tel = bucketing.telemetry()
    tel.reset()

    cfg = ServeConfig(max_batch=max_batch, queue_limit=512,
                      default_deadline_s=1.0)
    reg = ModelRegistry(cfg)
    reg.register("slo", model, warm=True)          # import -> AOT warm
    compiles_warm = tel.compiles("mln.output")

    rs = np.random.RandomState(0)
    sizes = [1, 2, 3, 5, 8]
    reqs = [rs.rand(s, n_feat).astype(np.float32) for s in sizes]
    tracker = slo.slo_tracker()

    def closed_loop(worker, conc, duration, deadline_s):
        """conc threads, each submit-wait-resubmit until the window ends."""
        stats = {"ok": 0, "rows": 0, "shed": 0}
        lock = threading.Lock()
        stop = time.perf_counter() + duration

        def loop(tid):
            i = tid
            while time.perf_counter() < stop:
                try:
                    out = worker.submit(reqs[i % len(reqs)],
                                        deadline_s=deadline_s)
                    with lock:
                        stats["ok"] += 1
                        stats["rows"] += len(out)
                except ShedError:
                    with lock:
                        stats["shed"] += 1
                i += 1

        t0 = time.perf_counter()
        threads = [threading.Thread(target=loop, args=(t,), daemon=True)
                   for t in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats["dt"] = time.perf_counter() - t0
        return stats

    ramp = []
    try:
        for conc in levels:
            # Per-level worker => per-level route; the shared LatencyModel
            # keeps admission estimates warm across levels.
            name = "slo_c%d" % conc
            worker = reg.register(name, model, warm=False)
            st = closed_loop(worker, conc, window_s, deadline_s=1.0)
            hist = tracker._hist.summary(route="serve." + name) or {}
            ramp.append({
                "concurrency": conc,
                "rps": round(st["ok"] / st["dt"], 1),
                "rows_per_s": round(st["rows"] / st["dt"], 1),
                "shed": st["shed"],
                "p50_ms": round(hist.get("p50", 0.0) * 1e3, 3),
                "p99_ms": round(hist.get("p99", 0.0) * 1e3, 3),
            })
            if not _budget_left():
                break

        sat = max(ramp, key=lambda r: r["rps"])

        # Forced-overload arm: starved queue, 4x saturation concurrency,
        # tight deadline. MUST shed and MUST move the burn-rate gauge.
        over_cfg = ServeConfig(max_batch=max(4, max_batch // 4),
                               queue_limit=2, default_deadline_s=0.05)
        over = ModelWorker("slo_overload", model, config=over_cfg,
                           latency=reg.latency)
        try:
            ost = closed_loop(over, max(8, 4 * sat["concurrency"]),
                              window_s, deadline_s=0.05)
        finally:
            over.shutdown()
        over_route = "serve.slo_overload"
        overload = {
            "ok": ost["ok"],
            "shed": ost["shed"],
            "shed_total": int(tracker._count.value(
                route=over_route, status="shed") or 0),
            "burn_rate": tracker.burn_rate(over_route) or 0.0,
        }
    finally:
        reg.shutdown()

    return {
        "metric": "serving_slo_p99",
        "value": sat["p99_ms"],
        "unit": "ms",
        "saturation_rps": sat["rps"],
        "saturation_rows_per_s": sat["rows_per_s"],
        "saturation_concurrency": sat["concurrency"],
        "p50_ms_at_saturation": sat["p50_ms"],
        "ramp": ramp,
        "buckets_used": len(
            tel.buckets_used("serve.slo_c%d" % sat["concurrency"])),
        "compiles_warm": compiles_warm,
        "request_path_compiles": tel.compiles("mln.output") - compiles_warm,
        "overload": overload,
        "slo": {"threshold_ms": tracker.threshold_s * 1e3,
                "objective": tracker.objective},
        "note": "p99 at saturation from dl4j_request_seconds quantiles; "
                "overload arm gates shed>0 and burn-rate reaction",
    }


def bench_generate():
    """Generative-serving bench — the token-level continuous-batching decode
    engine (serve/scheduler.GenerateWorker) under an OPEN-LOOP load
    generator: arrivals fire on a fixed schedule regardless of completions,
    so queueing delay shows up in TTFT instead of being absorbed by a
    closed loop's back-off.

    Three phases:
      ramp      arrival-rate sweep (streams/sec); per-level TTFT/ITL
                quantiles from the SLO tracker's dl4j_ttft_seconds /
                dl4j_itl_seconds P^2 series and tokens/s from the
                dl4j_tokens_generated_total counter delta.
      headline  p99 TTFT (ms) at the highest-tokens/s level.
      overload  a starved engine (queue_limit=2, decode_batch_max=2) under
                a deliberately hopeless deadline + arrival blast; gates
                that the engine SHEDS and the burn-rate gauge reacts.

    Also gates the decode AOT contract: after register_generate's warm,
    the whole load run must add ZERO compiles at the decode.step site."""
    import threading
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.nn.model import MultiLayerNetwork
    from deeplearning4j_tpu.obs import slo
    from deeplearning4j_tpu.serve import (
        GenerateConfig, ModelRegistry, ShedError)
    from deeplearning4j_tpu.utils import bucketing

    vocab, d_model, n_blocks, max_len = 64, 64, 2, 256
    rates = [2.0, 6.0, 12.0]        # streams/sec, open-loop
    window_s = 3.0
    max_new = 24
    if SMOKE:
        d_model, max_len = 32, 64
        rates = [4.0]
        window_s = 0.6
        max_new = 6

    model = MultiLayerNetwork(TransformerLM(
        vocab_size=vocab, max_len=max_len, d_model=d_model, n_heads=4,
        n_blocks=n_blocks, dtype="float32"))
    model.init()
    tel = bucketing.telemetry()
    tel.reset()

    cfg = GenerateConfig(decode_batch_max=8, kv_page_tokens=16,
                         prefill_chunk=16, max_new_default=max_new,
                         queue_limit=256, default_deadline_s=30.0)
    reg = ModelRegistry()
    worker = reg.register_generate("gen", model, warm=True, config=cfg)
    compiles_warm = tel.compiles("decode.step")
    tracker = slo.slo_tracker()

    rs = np.random.RandomState(0)
    prompt_lens = [4, 9, 17, 30]
    prompts = [rs.randint(0, vocab, size=n).tolist() for n in prompt_lens]

    def open_loop(w, rate, duration, deadline_s=None):
        """Fire submissions on the arrival clock; each stream is consumed
        by its own thread (the consumer IS the chunked-HTTP reader)."""
        stats = {"streams": 0, "tokens": 0, "shed": 0, "shed_mid": 0}
        lock = threading.Lock()
        threads = []

        def consume(i):
            try:
                s = w.submit(prompts[i % len(prompts)], max_new=max_new,
                             deadline_s=deadline_s)
                toks = list(s)
                with lock:
                    stats["streams"] += 1
                    stats["tokens"] += len(toks)
                    if s.finish_reason == "shed:deadline":
                        stats["shed_mid"] += 1
            except ShedError:
                with lock:
                    stats["shed"] += 1

        t0 = time.perf_counter()
        n = int(rate * duration)
        for i in range(n):
            wait = t0 + i / rate - time.perf_counter()
            if wait > 0:
                time.sleep(wait)
            t = threading.Thread(target=consume, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=60)
        stats["dt"] = time.perf_counter() - t0
        return stats

    def tok_count(route):
        return int(tracker._tokens.value(route=route) or 0)

    route = "generate.gen"
    ramp = []
    try:
        for rate in rates:
            tk0 = tok_count(route)
            st = open_loop(worker, rate, window_s)
            ttft = tracker._ttft.summary(route=route) or {}
            itl = tracker._itl.summary(route=route) or {}
            ramp.append({
                "arrival_rate": rate,
                "streams": st["streams"],
                "tokens_per_s": round((tok_count(route) - tk0) / st["dt"], 1),
                "ttft_p50_ms": round(ttft.get("p50", 0.0) * 1e3, 3),
                "ttft_p99_ms": round(ttft.get("p99", 0.0) * 1e3, 3),
                "itl_p50_ms": round(itl.get("p50", 0.0) * 1e3, 3),
                "itl_p99_ms": round(itl.get("p99", 0.0) * 1e3, 3),
                "shed": st["shed"],
            })
            if not _budget_left():
                break

        sat = max(ramp, key=lambda r: r["tokens_per_s"])
        # the zero-compile gate closes HERE: the overload worker below is
        # deliberately cold (warm=False) and its compiles are its own
        request_path_compiles = tel.compiles("decode.step") - compiles_warm

        # Overload arm: starved engine + hopeless deadline; after one
        # measured stream primes the ITL estimate, repriced admission MUST
        # shed (arrival or mid-stream) and move the burn-rate gauge.
        over_cfg = GenerateConfig(decode_batch_max=2, kv_page_tokens=16,
                                  prefill_chunk=16, max_new_default=max_new,
                                  queue_limit=2, default_deadline_s=30.0,
                                  min_samples=1)
        over = reg.register_generate("gen_over", model, warm=False,
                                     config=over_cfg)
        list(over.submit(prompts[0], max_new=max_new))  # prime the ITL model
        ost = open_loop(over, max(8.0, 4 * sat["arrival_rate"]),
                        min(window_s, 1.0), deadline_s=0.001)
        over_route = "generate.gen_over"
        overload = {
            "streams": ost["streams"],
            "shed_arrival": ost["shed"],
            "shed_midstream": ost["shed_mid"]
            + over.stats_counters["shed_midstream"],
            "shed_total": int(tracker._count.value(
                route=over_route, status="shed") or 0),
            "burn_rate": tracker.burn_rate(over_route) or 0.0,
        }
    finally:
        reg.shutdown()

    return {
        "metric": "generate_ttft_p99",
        "value": sat["ttft_p99_ms"],
        "unit": "ms",
        "tokens_per_s": sat["tokens_per_s"],
        "itl_p99_ms": sat["itl_p99_ms"],
        "arrival_rate_at_sat": sat["arrival_rate"],
        "ramp": ramp,
        "max_occupancy": worker.stats_counters["max_occupancy"],
        "generated_total": worker.stats_counters["generated"],
        "compiles_warm": compiles_warm,
        "request_path_compiles": request_path_compiles,
        "overload": overload,
        "note": "open-loop arrivals; TTFT/ITL from dl4j_ttft_seconds / "
                "dl4j_itl_seconds; overload arm gates shed>0 and burn-rate "
                "reaction; decode AOT gate: zero decode.step compiles after "
                "warm",
    }


def _cpu_mesh_env(n: int = 8) -> dict:
    """Env forcing an n-device host-platform mesh (must be set before jax
    initializes) — the dp_comms microbench models an R-replica exchange on
    a single host, like tests/conftest.py's 8 virtual CPU devices."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    return env


def bench_dp_comms():
    """Tentpole probe — data-parallel gradient-exchange arms on an 8-replica
    mesh (virtual CPU devices; the ratios are static byte accounting, the
    step times are relative sanity only on CPU):

      dense      implicit XLA psum + replicated update (the default path)
      sharded    explicit reduce-scatter -> 1/R-shard update -> all-gather
      compressed ternary threshold encoding, replicated update
      comp+shard both — the full DCN-lean configuration

    Headline value is the gradient wire-byte reduction of comp+shard vs the
    dense all-reduce (the ISSUE gate: >= 4x; ternary packing gives 16x
    modulo shard padding). Param all-gather bytes are reported separately."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.parallel import MeshSpec, ParallelWrapper, make_mesh

    R = min(8, jax.device_count())
    n_feat, hidden, classes, batch = 64, 512, 10, 8 * R
    steps = 2 if SMOKE else 20
    if SMOKE:
        hidden = 32

    def build():
        conf = MultiLayerConfiguration(
            layers=(Dense(n_out=hidden, activation="tanh"),
                    OutputLayer(n_out=classes, activation="softmax")),
            input_type=InputType.feed_forward(n_feat),
            updater={"type": "adam", "lr": 0.01},
            seed=7,
        )
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(0)
    x = rs.rand(batch, n_feat).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)]
    mesh = make_mesh(MeshSpec(data=R))

    arms = {}
    stats = {}
    for arm, (comp, shard) in (
        ("dense", (False, False)),
        ("sharded", (False, True)),
        ("compressed", (True, False)),
        ("compressed_sharded", (True, True)),
    ):
        model = build()
        pw = ParallelWrapper(model, mesh=mesh, grad_compress=comp,
                             sharded_update=shard, compress_threshold=1e-3)
        pw._replicate_model()
        xs, ys = pw._shard(x), pw._shard(y)
        runner = pw._exchange_runner()
        if runner is not None:
            runner.begin()
            step = lambda: runner.fit_batch(xs, ys, None, None)
        else:
            step = lambda: model._fit_batch(xs, ys, None, None)

        def run(n):
            loss = None
            for _ in range(n):
                loss = step()
            float(loss)  # value fetch: the only sync the tunnel cannot elide

        dt, n_done = _timed(run, warmup_steps=2, steps=steps)
        arms[arm] = round(n_done * batch / dt, 1)
        # dense/implicit moves every gradient once (psum payload)
        stats[arm] = (runner.comm_stats() if runner is not None else None)
        if runner is not None:
            runner.finish()

    full = stats["compressed_sharded"]
    ratio = full["dense_bytes"] / max(full["wire_bytes"], 1)
    return {
        "metric": "dp_comms_grad_bytes_reduction",
        "value": round(ratio, 1),
        "unit": "x (dense grad bytes / compressed wire bytes, per step)",
        "replicas": R,
        "grad_dense_bytes": full["dense_bytes"],
        "grad_wire_bytes": full["wire_bytes"],
        "param_allgather_bytes": full["param_bytes"],
        "arms_samples_per_sec": arms,
        "note": ("virtual-CPU mesh: byte counts are exact (static), step "
                 "times are relative sanity only"),
    }


def bench_mesh_mfu():
    """MULTICHIP promoted (ISSUE 13) — the ONE mesh step program across
    (data, tensor, stage) shapes on an R-device mesh. Each arm trains the
    same MLP from the same seed on the same batch through
    parallel/mesh_step.MeshTrainer: params per the TP rules, optimizer
    moments sharded over the spare axes (arXiv 2004.13336), the gradient
    all-reduce rewritten per shape by GSPMD.

    Gates (tools/bench_smoke.sh):
      gate_tuned_ge_dp_baseline        the best measured shape >= the
                                       pure-DP (d=R,t=1,s=1) default —
                                       holds by construction (the default
                                       is in the race), which is the same
                                       contract the knob registry gives
                                       every tuned default
      gate_shape_parity                fixed-step losses match across every
                                       shape (same math, different layout)
      gate_zero_steady_state_compiles  no mln.step re-traces inside any
                                       arm's measured loop (the output
                                       sharding constraints pin the layout)

    dl4j_mfu per shape lands when the backend has a roofline (TPU); on the
    CPU smoke mesh the throughput ratios carry the gates and MFU is omitted
    rather than fabricated."""
    import jax

    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.parallel import MeshSpec, MeshTrainer
    from deeplearning4j_tpu.utils import bucketing

    R = min(8, jax.device_count())
    n_feat, hidden, classes = 64, (32 if SMOKE else 512), 10
    batch = 8 * R
    shapes = [(R, 1, 1)]
    if R >= 2 and R % 2 == 0:
        shapes += [(R // 2, 2, 1), (R // 2, 1, 2)]
    if R >= 4 and R % 4 == 0:
        shapes.append((R // 4, 2, 2))

    def build():
        conf = MultiLayerConfiguration(
            layers=(Dense(n_out=hidden, activation="tanh"),
                    OutputLayer(n_out=classes, activation="softmax")),
            input_type=InputType.feed_forward(n_feat),
            updater={"type": "adam", "lr": 0.01},
            seed=7,
        )
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(0)
    x = rs.rand(batch, n_feat).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)]

    peak = _peak_flops("bfloat16")
    # analytic train FLOPs (2*MACs forward, x3 fwd+bwd), GLOBAL per step —
    # layout-independent, so cross-shape MFU compares pure efficiency
    train_flops = 3.0 * 2.0 * batch * (n_feat * hidden + hidden * classes)

    tel = bucketing.telemetry()
    arms, mfu, probes, retraces = {}, {}, {}, {}
    for d, t, s in shapes:
        key = f"d{d}t{t}s{s}"
        trainer = MeshTrainer(build(), MeshSpec(data=d, model=t, pipe=s))
        # fixed-step parity probe (compiles land here, outside the timing)
        probes[key] = [round(float(trainer.fit_batch(x, y)), 6)
                       for _ in range(3)]
        traced = tel.traces.get("mln.step", 0)

        def run(n, fit=trainer.fit_batch):
            loss = None
            for _ in range(n):
                loss = fit(x, y)
            float(loss)  # value fetch: the only sync the tunnel cannot elide

        dt, n_done = _timed(run, warmup_steps=1, steps=2 if SMOKE else 20)
        retraces[key] = tel.traces.get("mln.step", 0) - traced
        sps = n_done * batch / dt
        arms[key] = round(sps, 1)
        if peak:
            mfu[key] = round(train_flops * (sps / batch) / (peak * R), 4)
        trainer.finish()

    base_key = f"d{R}t1s1"
    best_key = max(arms, key=arms.get)
    base = np.asarray(probes[base_key])
    dev = max(float(np.max(np.abs(np.asarray(p) - base)
                           / np.maximum(np.abs(base), 1e-9)))
              for p in probes.values())
    out = {
        "metric": "mesh_step_tuned_vs_dp",
        "value": round(arms[best_key] / max(arms[base_key], 1e-9), 3),
        "unit": "x samples/sec, best (d,t,s) over pure-DP (d=R,t=1,s=1)",
        "devices": R,
        "tuned_shape": best_key,
        "arms_samples_per_sec": arms,
        "shape_losses": probes,
        "parity_max_rel_dev": round(dev, 8),
        "steady_state_retraces": retraces,
        "gate_tuned_ge_dp_baseline": arms[best_key] >= arms[base_key],
        "gate_shape_parity": dev < 1e-3,
        "gate_zero_steady_state_compiles": all(
            v == 0 for v in retraces.values()),
    }
    if mfu:
        out["dl4j_mfu"] = mfu
        # land the per-shape MFU in the live gauge the cost layer owns
        from deeplearning4j_tpu.obs import metrics as obs_metrics

        g = obs_metrics.registry().gauge(
            "dl4j_mfu", "model FLOPs utilization: achieved flops/s at the "
            "site's step span over the bf16 roofline", ("site",))
        for k, v in mfu.items():
            g.set(v, site=f"mesh.step.{k}")
    return out


def bench_checkpoint():
    """Durable-checkpoint cycle (docs/ROBUSTNESS.md): atomic full-state save
    (tmp+fsync+rename, CRC over the final bytes) -> CRC validation ->
    full-state restore into a fresh model. The fsync makes this a real
    durability number, not a page-cache write; headline is the end-to-end
    cycle time for a ~1.1M-param MLP (what a save_every_n_iterations
    listener adds to a training step when it fires)."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.train import resilience

    n_feat, hidden, classes, batch = 64, (32 if SMOKE else 1024), 10, 32
    conf = MultiLayerConfiguration(
        layers=(Dense(n_out=hidden, activation="tanh"),
                OutputLayer(n_out=classes, activation="softmax")),
        input_type=InputType.feed_forward(n_feat),
        updater={"type": "adam", "lr": 0.01},
        seed=7,
    )
    model = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.rand(batch, n_feat).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)]
    model.fit((x, y), epochs=1, batch_size=batch)  # populate opt state

    workdir = tempfile.mkdtemp(prefix="bench_ckpt_")
    path = os.path.join(workdir, "checkpoint.zip")
    target = MultiLayerNetwork(conf).init()
    phases = {"save": [], "validate": [], "restore": []}
    try:
        def cycle(n):
            for _ in range(n):
                t0 = time.perf_counter()
                info = resilience.save_checkpoint(model, path)
                t1 = time.perf_counter()
                ok = resilience.validate_checkpoint(
                    path, crc=info["crc"], size=info["size"])
                t2 = time.perf_counter()
                resilience.load_state_into(target, path)
                t3 = time.perf_counter()
                if not ok:
                    raise RuntimeError("checkpoint failed its own CRC")
                phases["save"].append(t1 - t0)
                phases["validate"].append(t2 - t1)
                phases["restore"].append(t3 - t2)

        dt, n_done = _timed(cycle, warmup_steps=1, steps=2 if SMOKE else 10)
        size = os.path.getsize(path)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    med = {k: round(1e3 * sorted(v)[len(v) // 2], 2)
           for k, v in phases.items() if v}
    return {
        "metric": "checkpoint_cycle_ms",
        "value": round(1e3 * dt / n_done, 2),
        "unit": "ms per save+validate+restore cycle (fsync durable)",
        "checkpoint_bytes": size,
        "phase_median_ms": med,
        "params": sum(int(np.prod(s)) for s in (
            (n_feat, hidden), (hidden,), (hidden, classes), (classes,))),
    }


def bench_mnist_mlp():
    """Observability-overhead arm (ISSUE 5 gate: <= 2%): the SAME compiled
    MNIST-shape MLP fit loop with the full obs layer live (spans + registry
    + JSONL event log) vs DL4J_TPU_OBS=0. The env knob is read per call, so
    both arms share one process, one model and one executable — the delta
    is the layer itself, not compile or allocator noise."""
    import shutil
    import tempfile

    from deeplearning4j_tpu import obs
    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)

    n_feat, hidden, classes, batch = 784, (32 if SMOKE else 256), 10, 128
    n_batches = 4 if SMOKE else 64
    epochs = 1 if SMOKE else 3
    conf = MultiLayerConfiguration(
        layers=(Dense(n_out=hidden, activation="relu"),
                OutputLayer(n_out=classes, activation="softmax")),
        input_type=InputType.feed_forward(n_feat),
        updater={"type": "sgd", "lr": 0.05},
        seed=7,
    )
    model = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    n = batch * n_batches
    X = rs.rand(n, n_feat).astype(np.float32)
    Y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, n)]

    workdir = tempfile.mkdtemp(prefix="bench_obs_")
    old = os.environ.get("DL4J_TPU_OBS")

    def arm(on: bool) -> float:
        os.environ["DL4J_TPU_OBS"] = "1" if on else "0"
        t0 = time.perf_counter()
        model.fit((X, Y), epochs=epochs, batch_size=batch)
        return time.perf_counter() - t0

    try:
        obs.configure_event_log(os.path.join(workdir, "events.jsonl"))
        arm(True)    # warmup: compiles + first-touch of span/event paths
        arm(False)
        on_times, off_times = [], []
        for _ in range(1 if SMOKE else 3):
            off_times.append(arm(False))
            on_times.append(arm(True))
            if _budget_left() <= 0:
                break
    finally:
        if old is None:
            os.environ.pop("DL4J_TPU_OBS", None)
        else:
            os.environ["DL4J_TPU_OBS"] = old
        obs.configure_event_log(None)
        shutil.rmtree(workdir, ignore_errors=True)
    t_on = sorted(on_times)[len(on_times) // 2]
    t_off = sorted(off_times)[len(off_times) // 2]
    overhead = (t_on - t_off) / t_off
    steps = epochs * n_batches
    # the cost report must resolve BEFORE the tuner arm's subprocesses run
    # (the lazy exemplars weakref the jitted step fn of THIS process)
    cost = obs.cost_report()
    tuner = _mnist_tuner_arm(model, X[:batch], Y[:batch])
    return {
        "metric": "mnist_mlp_obs_overhead",
        "value": round(100.0 * overhead, 2),
        "unit": "% fit wall-time, obs on vs DL4J_TPU_OBS=0 (gate: <= 2%)",
        "obs_on_samples_per_sec": round(steps * batch / t_on, 1),
        "obs_off_samples_per_sec": round(steps * batch / t_off, 1),
        "reps": len(on_times),
        "batches_per_arm": steps,
        # resolved while the model is still alive: the lazy cost exemplars
        # weakref the jitted step fn, so report-time resolution must happen
        # before the bench returns and drops it
        "cost": cost,
        "tuner": tuner,
    }


def _mnist_tuner_arm(model, x, y) -> dict:
    """Auto-tuner gate arm (ISSUE 9): successive-halving search over a
    small knob subspace for the SAME MLP, each trial in a fresh subprocess,
    winner persisted to a scratch tuning DB (the real flow, pointed at a
    temp path so a bench run never pollutes the user's DB). The gate is
    tuned >= default at EQUAL step budgets: when the measured winner is not
    the default it is re-confirmed head-to-head, and a winner that fails to
    reproduce is reverted to the default — tuning never ships a config it
    cannot defend, so the gate holds by construction and honestly."""
    import shutil
    import tempfile

    if _budget_left() < 15.0:
        return {"skipped": "bench budget exhausted before tuner arm"}
    from deeplearning4j_tpu import tune
    from deeplearning4j_tpu.tune import search as tsearch
    from deeplearning4j_tpu.tune import trial as ttrial

    workdir = tempfile.mkdtemp(prefix="bench_tune_")
    try:
        db = tune.TuningDB(os.path.join(workdir, "tunedb.zip"))
        overrides = ({"grad_accum": [1, 2]} if SMOKE else
                     {"grad_accum": [1, 2], "chain_steps": ["auto", "8"]})
        timeout = max(60.0, min(_budget_left() + 60.0, 600.0))
        entry = tune.tune_model(
            model, x, y, knob_names=tuple(overrides), overrides=overrides,
            db=db, base_steps=(2 if SMOKE else 8), warmup_steps=1,
            timeout_s=timeout)
        defaults = {n: tune.get(n).default for n in overrides}
        chosen = dict(entry["knobs"])
        tuned_obj = default_obj = entry["objective"]["steps_per_sec"]
        ratio, reverted = 1.0, False
        if chosen != defaults:
            spec = ttrial.build_spec(model, x, y, steps=(2 if SMOKE else 16),
                                     warmup_steps=1)
            confirm_def = tsearch.run_subprocess_trial(
                spec, defaults, timeout_s=timeout)
            confirm_tuned = tsearch.run_subprocess_trial(
                spec, chosen, timeout_s=timeout)
            default_obj = confirm_def.objective
            tuned_obj = confirm_tuned.objective
            ratio = (tuned_obj / default_obj) if default_obj > 0 else 0.0
            if not confirm_tuned.ok or ratio < 1.0:
                chosen, tuned_obj, ratio = defaults, default_obj, 1.0
                reverted = True
        return {
            "chosen_knobs": chosen,
            "default_knobs": defaults,
            "tuned_steps_per_sec": round(tuned_obj, 1),
            "default_steps_per_sec": round(default_obj, 1),
            "tuned_vs_default": round(ratio, 3),
            "gate_tuned_ge_default": ratio >= 1.0,
            "reverted_to_default": reverted,
            "trials": entry["trials"],
            "db_persisted": os.path.exists(db.path),
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _cold_start_arm(arm: str, workdir: str) -> dict:
    """One cold-start measurement arm, executed in a FRESH process (spawned
    by bench_cold_start): builds the model from nothing and reports phase
    timings for the serving path (time-to-first-request) and the training
    path (time-to-first-step). ``prep`` is the offline arm that warms the
    ladder and persists the executable bundle the ``bundle`` arm restores."""
    from deeplearning4j_tpu.nn import aot
    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.utils import bucketing

    n_feat, hidden, classes, batch = 32, (16 if SMOKE else 64), 10, 16
    conf = MultiLayerConfiguration(
        layers=(Dense(n_out=hidden, activation="relu"),
                OutputLayer(n_out=classes, activation="softmax")),
        input_type=InputType.feed_forward(n_feat),
        updater={"type": "sgd", "lr": 0.05},
        seed=7,
    )
    rs = np.random.RandomState(0)
    x = rs.rand(batch, n_feat).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)]
    req = rs.rand(5, n_feat).astype(np.float32)
    bundle = os.path.join(workdir, "cold_start.aotbundle")

    if arm == "prep":
        model = MultiLayerNetwork(conf).init()
        aot.warm_serving(model, batch)
        model.fit((x, y), epochs=1, batch_size=batch)  # warm hook compiles step
        info = aot.save_bundle(model, bundle)
        return {"arm": "prep", "saved": info is not None,
                "entries": (info or {}).get("entries", 0)}

    # the persistence gate (subprocess re-validation) is a once-per-backend
    # deployment decision whose verdict is stable for a given jaxlib; run it
    # outside the timers so the headline tracks the request path, and report
    # its cost separately
    t0 = time.perf_counter()
    validated = aot.persistence_allowed() if arm == "bundle" else None
    validation_ms = 1e3 * (time.perf_counter() - t0)

    tel = bucketing.telemetry()
    restored = 0
    t0 = time.perf_counter()
    model = MultiLayerNetwork(conf).init()
    if arm == "bundle":
        restored = aot.restore_bundle(model, bundle)
    # the ParallelInference ctor runs warm_serving itself when DL4J_TPU_AOT=1
    pi = ParallelInference(model, mode="batched", max_batch_size=batch)
    startup_ms = 1e3 * (time.perf_counter() - t0)

    c0 = tel.compiles("mln.output")
    t0 = time.perf_counter()
    out = pi.output(req)
    ttfr_ms = 1e3 * (time.perf_counter() - t0)
    request_compiles = tel.compiles("mln.output") - c0
    pi.shutdown()
    if out.shape != (len(req), classes):
        raise RuntimeError(f"bad serving output shape {out.shape}")

    fit_model = MultiLayerNetwork(conf).init()
    if arm == "bundle":
        restored += aot.restore_bundle(fit_model, bundle)
    c0 = tel.compiles("mln.step")
    t0 = time.perf_counter()
    fit_model.fit((x, y), epochs=1, batch_size=batch)
    ttfs_ms = 1e3 * (time.perf_counter() - t0)
    step_compiles = tel.compiles("mln.step") - c0

    from deeplearning4j_tpu import obs

    return {
        "arm": arm,
        "startup_ms": round(startup_ms, 1),
        "ttfr_ms": round(ttfr_ms, 1),
        "ttfs_ms": round(ttfs_ms, 1),
        "request_path_compiles": request_compiles,
        "fit_path_compiles": step_compiles,
        "restored_entries": restored,
        "validation_ms": round(validation_ms, 1),
        "persistence_validated": validated,
        # per-arm XLA cost + roofline view, resolved while the serving and
        # fit models are still alive (lazy exemplars weakref their targets)
        "cost": obs.cost_report(),
    }


def bench_cold_start():
    """Cold-start killer probe (AOT tentpole): time-to-first-request and
    time-to-first-step measured in FRESH subprocesses across three arms —

      none    lazy JIT only; the first request/step pays the XLA compile
      aot     DL4J_TPU_AOT=1; startup pre-compiles the bucket ladder, the
              first request is a warm dispatch (compile moved, not removed)
      bundle  AOT + executable bundle persisted by an offline ``prep`` arm
              and restored at startup: ZERO compiles anywhere on the
              request path (the acceptance gate)

    Headline is the warm-restore arm's TTFR; the gates (bundle TTFR
    strictly below no-AOT, zero request-path compiles) ride along so the
    trajectory catches regressions."""
    import shutil
    import subprocess
    import tempfile

    workdir = tempfile.mkdtemp(prefix="bench_cold_")
    timeout = (3 * _BUDGET_S + 300) if _BUDGET_S > 0 else 900
    here = os.path.abspath(__file__)

    def run_arm(arm: str) -> dict:
        env = dict(os.environ)
        # the tiny rng-free model would auto-chain its fit steps, which
        # bypasses per-step AOT dispatch by design — pin it off so the
        # arms compare the same dispatch path
        env["DL4J_TPU_CHAIN_STEPS"] = "0"
        env.pop("DL4J_TPU_AOT", None)
        env.pop("DL4J_TPU_AOT_BUNDLE", None)
        if arm != "none":
            env["DL4J_TPU_AOT"] = "1"
        if arm in ("prep", "bundle"):
            env["DL4J_TPU_AOT_BUNDLE"] = "1"
        try:
            proc = subprocess.run(
                [sys.executable, here, "--cold-arm", arm, "--cold-dir", workdir],
                capture_output=True, text=True, timeout=timeout, env=env,
                cwd=os.path.dirname(here))
        except subprocess.SubprocessError as e:
            return {"arm": arm, "error": f"{type(e).__name__}: {e}"[:300]}
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                obj = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(obj, dict):
                return obj
        return {"arm": arm,
                "error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}

    try:
        prep = run_arm("prep")
        arms = {a: run_arm(a) for a in ("none", "aot", "bundle")}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    ok = all("error" not in m for m in arms.values()) and "error" not in prep
    result = {
        "metric": "cold_start_ttfr_ms",
        "unit": "ms to first serving response, fresh process "
                "(AOT + restored executable bundle arm)",
        "prep": prep,
        "arms": arms,
    }
    if not ok:
        result["error"] = "one or more arms failed"
        return result
    result["value"] = arms["bundle"]["ttfr_ms"]
    result["ttfr_speedup_vs_no_aot"] = round(
        arms["none"]["ttfr_ms"] / max(arms["bundle"]["ttfr_ms"], 1e-3), 1)
    result["ttfs_speedup_vs_no_aot"] = round(
        arms["none"]["ttfs_ms"] / max(arms["bundle"]["ttfs_ms"], 1e-3), 1)
    result["gate_ttfr_bundle_lt_none"] = (
        arms["bundle"]["ttfr_ms"] < arms["none"]["ttfr_ms"])
    result["gate_zero_request_compiles"] = (
        arms["bundle"]["request_path_compiles"] == 0
        and arms["bundle"]["fit_path_compiles"] == 0)
    return result


def bench_vector_search():
    """ANN search-tier acceptance probe (search tentpole): a 100k x 64
    clustered corpus served by BOTH tiers of one :class:`VectorIndex` out
    of a COLD bundle-restored process. The build phase (fresh subprocess)
    trains the IVF coarse quantizer, warms the bucket-ladder grid and
    persists index + executable bundle; the measure phase (second fresh
    subprocess, compile cache empty) loads, restores, warms (all cache
    hits) and times single-query requests per tier — so the reported
    ``request_path_compiles`` is the real cold-process zero-compile gate,
    not an in-process approximation.

    Gates (asserted by tools/bench_smoke.sh):
      - corpus >= 100k vectors,
      - recall@10 of the IVF tier vs the exact tier >= 0.9,
      - IVF p99 strictly below exact-scan p99,
      - ZERO request-path compiles in the cold restored process.
    """
    import shutil
    import subprocess
    import tempfile
    import textwrap

    corpus_n, dim, n_centers = 100_000, 64, 256
    nlist, nprobe = 256, 8
    n_queries = 50 if SMOKE else 200
    timeout = (3 * _BUDGET_S + 300) if _BUDGET_S > 0 else 900
    workdir = tempfile.mkdtemp(prefix="bench_vecsearch_")

    # both phases regenerate the identical corpus/queries from the seed —
    # cheaper than shipping a 25MB npz and keeps each phase self-contained
    script = textwrap.dedent("""
        import json, os, sys, time
        import numpy as np
        os.environ["DL4J_TPU_AOT_BUNDLE"] = "1"
        from deeplearning4j_tpu.nn import aot
        from deeplearning4j_tpu.search import IndexConfig, VectorIndex

        phase, d = sys.argv[1], sys.argv[2]
        corpus_n, dim, n_centers = (int(a) for a in sys.argv[3:6])
        nlist, nprobe, n_q = (int(a) for a in sys.argv[6:9])
        ipath = os.path.join(d, "ix.zip")
        bpath = os.path.join(d, "ix.aotbundle")
        rs = np.random.RandomState(42)
        centers = (4.0 * rs.randn(n_centers, dim)).astype(np.float32)
        corpus = (centers[rs.randint(0, n_centers, corpus_n)]
                  + rs.randn(corpus_n, dim)).astype(np.float32)
        queries = (centers[rs.randint(0, n_centers, n_q)]
                   + rs.randn(n_q, dim)).astype(np.float32)
        if phase == "build":
            t0 = time.perf_counter()
            ix = VectorIndex.build(corpus, IndexConfig(
                dim=dim, nlist=nlist, nprobe=nprobe, max_k=16,
                batch_max=1, k_choices=(16,), train_sample=20000,
                kmeans_iters=8, pending_cap=0))
            build_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            warmed = ix.warm()
            warm_s = time.perf_counter() - t0
            aot.save_bundle(ix, bpath)
            ix.save(ipath)
            print(json.dumps({"build_s": round(build_s, 2),
                              "warm_s": round(warm_s, 2),
                              "warmed_executables": int(warmed)}))
        else:
            ix = VectorIndex.load(ipath)
            restored = aot.restore_bundle(ix, bpath)
            ix.warm()            # restored grid -> every rung a cache hit
            c0 = ix.program.compiles_observed()
            lat = {"exact": [], "ivf": []}
            ids = {"exact": [], "ivf": []}
            for tier in ("exact", "ivf"):
                for i in range(n_q):
                    q = queries[i:i + 1]
                    t0 = time.perf_counter()
                    got, _ = ix.search(q, k=10, tier=tier)
                    lat[tier].append((time.perf_counter() - t0) * 1e3)
                    ids[tier].append(np.asarray(got[0]))
            recall = float(np.mean([
                np.intersect1d(a[a >= 0], b[b >= 0]).size / 10.0
                for a, b in zip(ids["ivf"], ids["exact"])]))
            out = {"restored_executables": int(restored),
                   "request_path_compiles":
                       int(ix.program.compiles_observed() - c0),
                   "recall_at_10": round(recall, 4)}
            for tier in ("exact", "ivf"):
                a = np.asarray(lat[tier])
                out[tier + "_p50_ms"] = round(float(np.percentile(a, 50)), 3)
                out[tier + "_p99_ms"] = round(float(np.percentile(a, 99)), 3)
                out[tier + "_qps"] = round(n_q / (a.sum() / 1e3), 1)
            print(json.dumps(out))
    """)

    def run_phase(phase: str) -> dict:
        argv = [sys.executable, "-c", script, phase, workdir,
                str(corpus_n), str(dim), str(n_centers),
                str(nlist), str(nprobe), str(n_queries)]
        try:
            proc = subprocess.run(
                argv, capture_output=True, text=True, timeout=timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.SubprocessError as e:
            return {"error": f"{phase}: {type(e).__name__}: {e}"[:300]}
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                obj = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(obj, dict):
                return obj
        return {"error": f"{phase}: rc={proc.returncode}: "
                         f"{proc.stderr[-300:]}"}

    try:
        build = run_phase("build")
        serve = {} if "error" in build else run_phase("serve")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    result = {
        "metric": "vector_search_p99",
        "unit": "ms per single-query request, IVF tier, cold "
                "bundle-restored process",
        "corpus": corpus_n, "dim": dim, "queries_per_tier": n_queries,
        "nlist": nlist, "nprobe": nprobe,
    }
    result.update(build)
    result.update(serve)
    if "error" in result:
        return result
    result["value"] = result["ivf_p99_ms"]
    result["ivf_p99_speedup_vs_exact"] = round(
        result["exact_p99_ms"] / max(result["ivf_p99_ms"], 1e-3), 2)
    return result


_BENCHES = {
    "lenet5": bench_lenet5,
    "resnet50": bench_resnet50,
    "lstm": bench_lstm_char_rnn,
    "word2vec": bench_word2vec,
    "transformer": bench_transformer,
    "serving": bench_serving_mixed,
    "serving_slo": bench_serving_slo,
    "generate": bench_generate,
    "dp_comms": bench_dp_comms,
    "mesh_mfu": bench_mesh_mfu,
    "checkpoint": bench_checkpoint,
    "mnist_mlp": bench_mnist_mlp,
    "cold_start": bench_cold_start,
    "vector_search": bench_vector_search,
}

# benches that need a multi-device mesh regardless of the host's accelerator
# count — run on forced virtual CPU devices in their isolated subprocess
_CPU_MESH_BENCHES = {"dp_comms", "mesh_mfu"}


def _run_isolated(name: str) -> dict:
    """Run one sub-benchmark in a FRESH process. Sharing a process is not
    neutral: ResNet50's leftover HBM arena slows the LSTM executable ~18x
    (measured on v5e) — per-bench processes give each model a clean chip."""
    import subprocess
    import sys

    # kill-timeout derives from the per-metric budget: the budget bounds the
    # measure loops, the headroom covers compiles — and a budget-shrunk bench
    # exits with its JSON long before the kill lands (satellite fix for
    # round 5's lenet5 rc=124)
    timeout = (3 * _BUDGET_S + 300) if _BUDGET_S > 0 else 900
    env = _cpu_mesh_env() if name in _CPU_MESH_BENCHES else None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--only", name],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.SubprocessError as e:  # hang/timeouts must not sink the rest
        return {"metric": name, "error": f"{type(e).__name__}: {e}"[:300]}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(obj, dict):
            return obj
    return {"metric": name,
            "error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--only", choices=sorted(_BENCHES),
                    help="run ONE benchmark in-process (internal)")
    ap.add_argument("--in-process", action="store_true",
                    help="run all benchmarks in this process (no isolation)")
    ap.add_argument("--cold-arm", help=argparse.SUPPRESS)
    ap.add_argument("--cold-dir", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.cold_arm:  # internal: one cold-start arm in this fresh process
        try:
            print(json.dumps(_cold_start_arm(args.cold_arm, args.cold_dir)),
                  flush=True)
        except Exception as e:
            print(json.dumps({"arm": args.cold_arm,
                              "error": f"{type(e).__name__}: {e}"[:300]}))
        return

    # mesh-needing benches launched directly (not via _run_isolated) still
    # get their virtual devices — must land before jax initializes
    if args.only in _CPU_MESH_BENCHES:
        os.environ.update(_cpu_mesh_env())

    # DL4J_TPU_COMPILE_CACHE: persistent XLA cache (opt-in) — amortizes
    # the long-pole compiles (W2V epoch scan: 52.2s cold) across bench
    # processes; the cold/warm split stays honestly reported either way
    from deeplearning4j_tpu.utils.compile_cache import (
        enable_compilation_cache_from_env)

    enable_compilation_cache_from_env()

    # every result JSON carries the observability snapshot of the process
    # that MEASURED it (per-bench subprocesses: their own registry/spans)
    def _with_obs(m: dict) -> dict:
        from deeplearning4j_tpu import obs

        if "obs" not in m:
            m["obs"] = obs.snapshot()
        return m

    if args.only:
        _budget_start()
        # hard backstop: if a compile or measure loop wedges past every
        # soft budget check, raise INSIDE this process 60s before the
        # parent's kill-timeout (3*_BUDGET_S+300) so an error JSON still
        # reaches stdout — a skipped metric must report itself, never
        # rc=124 (guaranteed-JSON half of the lenet5 fix)
        import signal

        def _hard_stop(signum, frame):
            raise TimeoutError(
                f"bench '{args.only}' hit the hard deadline "
                f"(DL4J_TPU_BENCH_BUDGET_S={_BUDGET_S:g})")

        if _BUDGET_S > 0 and hasattr(signal, "SIGALRM"):
            signal.signal(signal.SIGALRM, _hard_stop)
            signal.alarm(int(3 * _BUDGET_S + 240))
        try:
            print(json.dumps(_with_obs(_BENCHES[args.only]())), flush=True)
        except BaseException as e:
            print(json.dumps({"metric": args.only,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
            if not isinstance(e, Exception):  # KeyboardInterrupt etc.
                raise
        finally:
            if _BUDGET_S > 0 and hasattr(signal, "SIGALRM"):
                signal.alarm(0)
        return

    extras = []
    for name, fn in _BENCHES.items():
        if args.in_process or SMOKE:
            _budget_start()
            try:
                m = _with_obs(fn())
            except Exception as e:
                m = {"metric": name, "error": f"{type(e).__name__}: {e}"[:300]}
        else:
            m = _run_isolated(name)
        extras.append(m)
        print(json.dumps(m), flush=True)

    headline = next((m for m in extras if m.get("metric") ==
                     "resnet50_224_train_throughput" and "value" in m),
                    next((m for m in extras if "value" in m), extras[0]))
    final = {k: headline.get(k) for k in ("metric", "value", "unit", "vs_baseline")}
    if "mfu" in headline:
        final["mfu"] = headline["mfu"]
    final["extras"] = extras
    print(json.dumps(final))


if __name__ == "__main__":
    main()
